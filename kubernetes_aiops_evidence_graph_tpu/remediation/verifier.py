"""Remediation verifier — did the action actually help?

Parity with the reference RemediationVerifier (verifier.py:24-193): compares
error-rate and restart signals before vs after (the reference diffs now vs
``offset 15m`` PromQL; here before-values are captured at execution time and
compared against current backend state), checks pod health (Running +
Ready), and succeeds only when metrics improved AND pods are healthy
(:37-43).
"""
from __future__ import annotations

from typing import Any

from ..models import Incident, RemediationAction, VerificationResult


class RemediationVerifier:
    def __init__(self, backend: Any) -> None:
        self.backend = backend

    def capture_baseline(self, incident: Incident) -> dict:
        """Snapshot pre-remediation signals (the 'offset 15m' side)."""
        ns, svc = incident.namespace, incident.service or ""
        pods = self.backend.list_pods(ns, svc)
        return {
            "error_rate": self.backend.query_metric(ns, svc, "error_rate") or 0.0,
            "restarts": sum(p.restart_count for p in pods),
            "healthy_pods": sum(
                1 for p in pods if p.phase == "Running" and p.ready),
            "total_pods": len(pods),
        }

    def verify(
        self,
        incident: Incident,
        action: RemediationAction,
        baseline: dict | None = None,
    ) -> VerificationResult:
        ns, svc = incident.namespace, incident.service or ""
        before = baseline or {}
        pods = self.backend.list_pods(ns, svc)
        healthy_after = sum(1 for p in pods if p.phase == "Running" and p.ready)
        restarts_after = sum(p.restart_count for p in pods)
        error_after = self.backend.query_metric(ns, svc, "error_rate") or 0.0

        error_before = before.get("error_rate", 0.0)
        restarts_before = before.get("restarts", 0)
        healthy_before = before.get("healthy_pods", 0)

        metrics_improved = (
            error_after <= error_before and restarts_after <= restarts_before
        )
        pods_healthy = len(pods) > 0 and healthy_after == len(pods)
        success = bool(metrics_improved and pods_healthy)  # verifier.py:37-43

        return VerificationResult(
            action_id=action.id,
            incident_id=incident.id,
            success=success,
            metrics_improved=metrics_improved,
            error_rate_before=error_before,
            error_rate_after=error_after,
            restart_count_before=int(restarts_before),
            restart_count_after=int(restarts_after),
            pods_healthy_before=int(healthy_before),
            pods_healthy_after=int(healthy_after),
            verification_details={
                "total_pods": len(pods),
                "action_type": action.action_type.value,
            },
        )
