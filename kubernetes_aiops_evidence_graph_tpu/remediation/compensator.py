"""Saga compensation — roll a failed remediation's cluster effect back.

The reference workflow files a ticket when verification fails and walks
away, leaving the mutated cluster state standing (incident_workflow.py's
verify→create_ticket tail). graft-saga closes the loop: a FAILED
verification triggers a policy-gated, journaled compensation per action
type —

* ``scale_replicas``       → restore the pre-action replica count that
                             the executor captured at execute time
                             (``execution_result["prev_replicas"]``)
* ``cordon_node``          → uncordon
* ``rollback_deployment``  → re-rollback (the backend swap restores the
                             pre-action template)
* restart-class            → self-healing no-op (deleting a pod or
                             bouncing a deployment leaves nothing to
                             undo)

Compensation executes through the same two-phase RemediationExecutor
ledger (key = ``<original>:comp``), so a crash mid-compensation
reconciles instead of double-firing. Attempts are bounded
(settings.remediation_compensation_attempts); exhaustion — or a policy
denial — escalates to a human via an ``escalate_to_human`` action row.

The policy gate is PolicyEngine.evaluate_compensation: compensation
restores the pre-action state of an action the policy already allowed
and a human (or dev auto-approve) already approved, so the gate asks
whether the ORIGINAL action type is still env-allowlisted and the
namespace unprotected — not whether the inverse action (e.g. the
HIGH_RISK ``uncordon_node``) would be allowed as a fresh proposal.
"""
from __future__ import annotations

from typing import Any, Callable

from ..config import Settings, get_settings
from ..models import ActionStatus, ActionType, RemediationAction
from ..observability import get_logger
from ..observability import metrics as obs_metrics
from ..policy import PolicyEngine
from .executor import RESTART_CLASS, RemediationExecutor

log = get_logger("remediation.compensator")


class RemediationCompensator:
    def __init__(self, backend: Any, settings: Settings | None = None,
                 db: Any = None, policy: PolicyEngine | None = None,
                 fault_hook: "Callable[[str], None] | None" = None) -> None:
        self.backend = backend
        self.settings = settings or get_settings()
        self.db = db
        self.policy = policy or PolicyEngine()
        self.fault_hook = fault_hook

    def plan(self, action: RemediationAction) -> RemediationAction | None:
        """The inverse action, or None when the class self-heals."""
        if action.action_type in RESTART_CLASS:
            return None
        result = action.execution_result or {}
        inverse: ActionType | None = None
        params: dict[str, Any] = {}
        if action.action_type == ActionType.SCALE_REPLICAS:
            prev = result.get("prev_replicas")
            if prev is None:
                return None  # pre-ledger action rows carry no baseline
            inverse = ActionType.SCALE_REPLICAS
            params = {"replicas": int(prev)}
        elif action.action_type == ActionType.CORDON_NODE:
            inverse = ActionType.UNCORDON_NODE
        elif action.action_type == ActionType.ROLLBACK_DEPLOYMENT:
            inverse = ActionType.ROLLBACK_DEPLOYMENT
        if inverse is None:
            return None
        return RemediationAction(
            incident_id=action.incident_id,
            hypothesis_id=action.hypothesis_id,
            idempotency_key=f"{action.idempotency_key}:comp",
            action_type=inverse,
            target_resource=action.target_resource,
            target_namespace=action.target_namespace,
            target_cluster=action.target_cluster,
            parameters=params,
            risk_level=action.risk_level,
            blast_radius_score=action.blast_radius_score,
            environment=action.environment,
            status=ActionStatus.PROPOSED,
            status_reason=f"compensates {action.action_type.value}",
            requires_approval=False,  # covered by the original approval
            created_by="compensator",
        )

    def compensate(self, action: RemediationAction) -> dict:
        """Run the saga compensation for one executed-but-unverified
        action. Returns a journal-serializable outcome record."""
        at = action.action_type.value
        if action.action_type in RESTART_CLASS:
            obs_metrics.COMPENSATION_ACTIONS.inc(action_type=at,
                                                 outcome="noop")
            return {"compensated": False, "noop": True,
                    "reason": "restart-class actions self-heal"}
        gate = self.policy.evaluate_compensation(
            original_action_type=at,
            environment=self.settings.app_env,
            namespace=action.target_namespace)
        if not gate["allow"]:
            obs_metrics.COMPENSATION_ACTIONS.inc(action_type=at,
                                                 outcome="denied")
            self._escalate(action, f"compensation denied: {gate['reason']}")
            return {"compensated": False, "denied": True,
                    "reason": gate["reason"], "escalated": True}
        comp = self.plan(action)
        if comp is None:
            obs_metrics.COMPENSATION_ACTIONS.inc(action_type=at,
                                                 outcome="noop")
            self._escalate(action, "no compensation plan (missing baseline)")
            return {"compensated": False, "noop": True,
                    "reason": "no compensation plan", "escalated": True}
        attempts = max(int(getattr(self.settings,
                                   "remediation_compensation_attempts", 2)),
                       1)
        executor = RemediationExecutor(self.backend, self.settings,
                                       db=self.db,
                                       fault_hook=self.fault_hook)
        last_error = None
        for attempt in range(1, attempts + 1):
            if attempt > 1:
                # the ledger pinned the failed outcome under this key —
                # retry under an attempt-suffixed key (and a fresh row
                # id) so exactly-once holds per attempt, not per saga
                from uuid import uuid4
                comp.idempotency_key = (
                    f"{action.idempotency_key}:comp{attempt}")
                comp.id = uuid4()
            executed = executor.execute(comp)
            if self.db is not None:
                self.db.upsert_action(executed)
            if executed.status in (ActionStatus.COMPLETED,
                                   ActionStatus.SKIPPED):
                obs_metrics.COMPENSATION_ACTIONS.inc(action_type=at,
                                                     outcome="completed")
                action.status = ActionStatus.ROLLED_BACK
                action.status_reason = "compensated after failed verification"
                action.rollback_action_id = executed.id
                if self.db is not None:
                    self.db.upsert_action(action)
                    self.db.audit(str(action.incident_id),
                                  "action_compensated",
                                  {"action_type": at, "attempt": attempt,
                                   "compensation": comp.action_type.value})
                return {"compensated": True, "attempts": attempt,
                        "action_type": comp.action_type.value,
                        "result": executed.execution_result}
            last_error = executed.error_message
            log.warning("compensation_attempt_failed", attempt=attempt,
                        action_type=at, error=str(last_error))
        obs_metrics.COMPENSATION_ACTIONS.inc(action_type=at,
                                             outcome="failed")
        self._escalate(action,
                       f"compensation failed after {attempts} attempts: "
                       f"{last_error}")
        return {"compensated": False, "attempts": attempts,
                "error": last_error, "escalated": True}

    def _escalate(self, action: RemediationAction, reason: str) -> None:
        """Bounded attempts exhausted (or gate denied): leave a durable
        escalate_to_human action row + audit trail for the operator."""
        obs_metrics.COMPENSATION_ESCALATIONS.inc()
        log.error("compensation_escalated",
                  incident=str(action.incident_id), reason=reason)
        if self.db is None:
            return
        esc = RemediationAction(
            incident_id=action.incident_id,
            hypothesis_id=action.hypothesis_id,
            idempotency_key=f"{action.idempotency_key}:escalate",
            action_type=ActionType.ESCALATE_TO_HUMAN,
            target_resource=action.target_resource,
            target_namespace=action.target_namespace,
            status=ActionStatus.PENDING_APPROVAL,
            status_reason=reason,
            requires_approval=True,
            created_by="compensator",
        )
        self.db.upsert_action(esc)
        self.db.audit(str(action.incident_id), "compensation_escalated",
                      {"reason": reason,
                       "action_type": action.action_type.value})
