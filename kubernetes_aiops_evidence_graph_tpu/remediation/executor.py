"""Remediation executor — dispatches approved actions to the cluster.

Parity with the reference RemediationExecutor (executor.py:45-307): the same
dispatch table (restart_pod → delete the unhealthy-or-first pod, :86-134;
restart_deployment, :136-175; rollback to previous revision, :177-234;
scale with default current+1, :236-281; cordon, :283-307) — issued through
the ClusterAdminBackend interface, plus a dry-run mode and idempotent
execution the reference lacked.
"""
from __future__ import annotations

from typing import Any

from ..config import Settings, get_settings
from ..models import ActionStatus, ActionType, RemediationAction
from ..utils.timeutils import utcnow


class RemediationExecutor:
    def __init__(self, backend: Any, settings: Settings | None = None) -> None:
        self.backend = backend
        self.settings = settings or get_settings()
        self._executed_keys: set[str] = set()
        self._dispatch = {
            ActionType.RESTART_POD: self._restart_pod,
            ActionType.DELETE_POD: self._restart_pod,
            ActionType.RESTART_DEPLOYMENT: self._restart_deployment,
            ActionType.ROLLBACK_DEPLOYMENT: self._rollback_deployment,
            ActionType.SCALE_REPLICAS: self._scale_replicas,
            ActionType.CORDON_NODE: self._cordon_node,
        }

    def execute(self, action: RemediationAction) -> RemediationAction:
        if action.idempotency_key in self._executed_keys:
            action.status = ActionStatus.SKIPPED
            action.status_reason = "duplicate idempotency key"
            return action
        handler = self._dispatch.get(action.action_type)
        if handler is None:
            action.status = ActionStatus.SKIPPED
            action.status_reason = f"no executor for {action.action_type.value}"
            return action
        action.executed_at = utcnow()
        action.status = ActionStatus.EXECUTING
        if self.settings.remediation_dry_run:
            action.status = ActionStatus.COMPLETED
            action.completed_at = utcnow()
            action.execution_result = {"dry_run": True}
            self._executed_keys.add(action.idempotency_key)
            return action
        try:
            result = handler(action)
            action.execution_result = result
            action.status = (ActionStatus.COMPLETED if result.get("ok")
                             else ActionStatus.FAILED)
            if not result.get("ok"):
                action.error_message = result.get("error", "action failed")
        except Exception as exc:  # graft-audit: allow[broad-except] action-handler isolation: any failure marks the action FAILED
            action.status = ActionStatus.FAILED
            action.error_message = str(exc)
        action.completed_at = utcnow()
        self._executed_keys.add(action.idempotency_key)
        return action

    # -- handlers ---------------------------------------------------------

    def _restart_pod(self, action: RemediationAction) -> dict:
        ns = action.target_namespace
        pods = self.backend.list_pods(ns, action.target_resource)
        if not pods:
            # target may be a pod name rather than a service
            ok = self.backend.delete_pod(ns, action.target_resource)
            return {"ok": ok, "deleted": action.target_resource if ok else None}
        unhealthy = [p for p in pods if not p.ready or p.waiting_reason
                     or p.terminated_reason]
        victim = (unhealthy or pods)[0]  # unhealthy-or-first (:86-134)
        ok = self.backend.delete_pod(ns, victim.name)
        return {"ok": ok, "deleted": victim.name}

    def _restart_deployment(self, action: RemediationAction) -> dict:
        ok = self.backend.restart_deployment(action.target_namespace,
                                             action.target_resource)
        return {"ok": ok, "restarted": action.target_resource}

    def _rollback_deployment(self, action: RemediationAction) -> dict:
        ok = self.backend.rollback_deployment(action.target_namespace,
                                              action.target_resource)
        return {"ok": ok, "rolled_back": action.target_resource}

    def _scale_replicas(self, action: RemediationAction) -> dict:
        ns = action.target_namespace
        deploys = self.backend.list_deployments(ns, action.target_resource)
        if not deploys:
            return {"ok": False, "error": "deployment not found"}
        target = action.parameters.get("replicas", deploys[0].replicas + 1)  # :236-281
        ok = self.backend.scale_deployment(ns, deploys[0].name, int(target))
        return {"ok": ok, "replicas": int(target)}

    def _cordon_node(self, action: RemediationAction) -> dict:
        ok = self.backend.cordon_node(action.target_resource)
        return {"ok": ok, "cordoned": action.target_resource}
