"""Remediation executor — dispatches approved actions to the cluster.

Parity with the reference RemediationExecutor (executor.py:45-307): the same
dispatch table (restart_pod → delete the unhealthy-or-first pod, :86-134;
restart_deployment, :136-175; rollback to previous revision, :177-234;
scale with default current+1 clamped at remediation_max_scale_replicas,
:236-281; cordon, :283-307) — issued through the ClusterAdminBackend
interface, plus a dry-run mode and idempotent execution the reference
lacked.

graft-saga: execution is TWO-PHASE against the durable
``action_executions`` ledger when a Database is supplied. An intent row
(idempotency key + pre-action probe + verification baseline) commits
BEFORE the cluster mutation dispatches; the result row commits after. On
resume, a result row answers the execution from the ledger (the mutation
fired exactly once — never re-dispatched), and an intent WITHOUT a result
is IN-DOUBT: the crash landed between the mutation and the commit, so the
executor RECONCILES by probing cluster state (observed replicas / node
unschedulable / deployment revision / pod health) and only re-fires when
the probe proves the mutation never landed. The legacy in-memory
``_executed_keys`` set remains the dedup for ledgerless callers.
"""
from __future__ import annotations

from typing import Any, Callable

from ..config import Settings, get_settings
from ..models import ActionStatus, ActionType, RemediationAction
from ..observability import get_logger
from ..observability import metrics as obs_metrics
from ..utils.timeutils import utcnow

log = get_logger("remediation.executor")

# action classes for reconciliation/compensation: restart-class mutations
# are convergent (the controller re-creates what was deleted) and their
# compensation is a self-healing no-op
RESTART_CLASS = frozenset({
    ActionType.RESTART_POD, ActionType.DELETE_POD,
    ActionType.RESTART_DEPLOYMENT,
})


class RemediationExecutor:
    def __init__(self, backend: Any, settings: Settings | None = None,
                 db: Any = None,
                 fault_hook: "Callable[[str], None] | None" = None) -> None:
        self.backend = backend
        self.settings = settings or get_settings()
        self.db = db                    # action_executions ledger (storage)
        self.fault_hook = fault_hook    # chaos seam (rca/faults.py)
        self._executed_keys: set[str] = set()
        self.reconciliations = 0
        self._dispatch = {
            ActionType.RESTART_POD: self._restart_pod,
            ActionType.DELETE_POD: self._restart_pod,
            ActionType.RESTART_DEPLOYMENT: self._restart_deployment,
            ActionType.ROLLBACK_DEPLOYMENT: self._rollback_deployment,
            ActionType.SCALE_REPLICAS: self._scale_replicas,
            ActionType.CORDON_NODE: self._cordon_node,
            ActionType.UNCORDON_NODE: self._uncordon_node,
        }

    def _fault(self, stage: str) -> None:
        if self.fault_hook is not None:
            self.fault_hook(stage)

    def execute(self, action: RemediationAction,
                baseline: dict | None = None) -> RemediationAction:
        """Execute (or replay, or reconcile) one action. ``baseline`` is
        the pre-action verification snapshot — persisted into the intent
        row so a resumed run sees the PRE-mutation baseline instead of
        re-probing the already-mutated cluster."""
        handler = self._dispatch.get(action.action_type)
        if handler is None:
            action.status = ActionStatus.SKIPPED
            action.status_reason = f"no executor for {action.action_type.value}"
            return action
        if self.db is not None:
            return self._execute_ledgered(action, handler, baseline)
        if action.idempotency_key in self._executed_keys:
            action.status = ActionStatus.SKIPPED
            action.status_reason = "duplicate idempotency key"
            return action
        # graft-audit: allow[ledger-order] ledger-less mode (db=None): there is no intent store to write; the in-memory idempotency set above dedups within the process
        self._dispatch_one(action, handler)
        self._executed_keys.add(action.idempotency_key)
        return action

    def ledger_baseline(self, action: RemediationAction) -> dict | None:
        """The verification baseline captured when this key's intent was
        journaled (None when no intent exists yet)."""
        if self.db is None:
            return None
        intent = self.db.execution_state(action.idempotency_key)["intent"]
        if intent is None:
            return None
        return intent["detail"].get("baseline")

    # -- two-phase path ----------------------------------------------------

    def _execute_ledgered(self, action: RemediationAction, handler,
                          baseline: dict | None) -> RemediationAction:
        key = action.idempotency_key
        state = self.db.execution_state(key)
        if state["result"] is not None:
            # exactly-once: the mutation already fired and its outcome is
            # durable — adopt the recorded outcome instead of re-firing
            # (a SKIPPED answer here would derail the replayed workflow's
            # verify/close conditions)
            rec = state["result"]
            action.status = ActionStatus(rec["status"])
            action.execution_result = rec["detail"].get("result")
            action.error_message = rec["detail"].get("error")
            action.status_reason = "replayed from action ledger"
            action.completed_at = utcnow()
            obs_metrics.ACTION_DUP_PREVENTED.inc()
            self._executed_keys.add(key)
            return action
        if state["intent"] is not None:
            # IN-DOUBT: intent journaled, no result — the crash landed
            # somewhere between dispatch and commit. Probe, never re-fire
            # blindly.
            return self._reconcile(action, handler, state["intent"])
        # fresh execution: intent (+ probe + baseline) BEFORE dispatch
        detail = {"pre": self._probe(action), "baseline": baseline}
        if action.action_type == ActionType.SCALE_REPLICAS:
            detail["target_replicas"] = self._scale_target(action)
        self.db.execution_intent(key, str(action.id),
                                 str(action.incident_id),
                                 action.action_type.value, detail)
        obs_metrics.ACTION_INTENTS.inc(
            action_type=action.action_type.value)
        self._dispatch_one(action, handler)
        self._fault("wf_execute")  # chaos: crash between mutation and commit
        self.db.execution_result(key, action.status.value, {
            "result": action.execution_result,
            "error": action.error_message,
        })
        self._executed_keys.add(key)
        return action

    def _dispatch_one(self, action: RemediationAction, handler) -> None:
        action.executed_at = utcnow()
        action.status = ActionStatus.EXECUTING
        if self.settings.remediation_dry_run:
            action.status = ActionStatus.COMPLETED
            action.completed_at = utcnow()
            action.execution_result = {"dry_run": True}
            return
        try:
            result = handler(action)
            action.execution_result = result
            action.status = (ActionStatus.COMPLETED if result.get("ok")
                             else ActionStatus.FAILED)
            if not result.get("ok"):
                action.error_message = result.get("error", "action failed")
        except Exception as exc:  # graft-audit: allow[broad-except] action-handler isolation: any failure marks the action FAILED
            action.status = ActionStatus.FAILED
            action.error_message = str(exc)
        action.completed_at = utcnow()

    # -- reconciliation (in-doubt intents) ---------------------------------

    def _probe(self, action: RemediationAction) -> dict:
        """Cluster-state observations reconciliation (and compensation)
        will compare against: replicas, deployment revision/image, node
        schedulability, unhealthy pod names."""
        ns = action.target_namespace
        pre: dict[str, Any] = {}
        try:
            if action.action_type in (ActionType.SCALE_REPLICAS,
                                      ActionType.ROLLBACK_DEPLOYMENT):
                deploys = self.backend.list_deployments(ns,
                                                        action.target_resource)
                if deploys:
                    pre["replicas"] = int(deploys[0].replicas)
                    pre["revision"] = int(getattr(deploys[0], "revision", 0))
                    pre["image"] = getattr(deploys[0], "image", None)
            elif action.action_type in (ActionType.CORDON_NODE,
                                        ActionType.UNCORDON_NODE):
                pre["unschedulable"] = self._node_unschedulable(
                    action.target_resource)
            elif action.action_type in RESTART_CLASS:
                pods = self.backend.list_pods(ns, action.target_resource)
                pre["unhealthy"] = sorted(
                    p.name for p in pods
                    if not p.ready or p.waiting_reason or p.terminated_reason)
        except (AttributeError, KeyError, TypeError, ValueError) as exc:
            pre["probe_error"] = str(exc)
        return pre

    def _node_unschedulable(self, name: str) -> bool | None:
        for node in self.backend.list_nodes():
            if node.name == name:
                return node.conditions.get("Unschedulable") == "True"
        return None

    def _reconcile(self, action: RemediationAction, handler,
                   intent: dict) -> RemediationAction:
        """Settle an in-doubt execution by probing whether the mutation
        landed. Landed → record the completed result (the crash ate only
        the commit). Provably not landed → re-fire ONCE through the
        normal dispatch (recorded as refired). Unknowable → fail the
        action and let compensation/escalation take it; a duplicate
        cluster mutation is the one outcome this path may never produce."""
        pre = intent["detail"].get("pre") or {}
        landed, result = self._probe_landed(action, pre)
        self.reconciliations += 1
        if landed:
            obs_metrics.ACTION_RECONCILED.inc(outcome="completed")
            log.info("action_reconciled_landed",
                     key=action.idempotency_key,
                     action_type=action.action_type.value)
            action.status = ActionStatus.COMPLETED
            action.execution_result = result
            action.completed_at = utcnow()
            action.status_reason = "reconciled: mutation had landed"
            self.db.execution_result(action.idempotency_key,
                                     action.status.value,
                                     {"result": result, "error": None,
                                      "reconciled": "landed"})
            self._executed_keys.add(action.idempotency_key)
            return action
        if landed is None:
            obs_metrics.ACTION_RECONCILED.inc(outcome="failed")
            log.warning("action_reconcile_unknowable",
                        key=action.idempotency_key)
            action.status = ActionStatus.FAILED
            action.error_message = "in-doubt execution not reconcilable"
            action.completed_at = utcnow()
            self.db.execution_result(action.idempotency_key,
                                     action.status.value,
                                     {"result": None,
                                      "error": action.error_message,
                                      "reconciled": "unknowable"})
            self._executed_keys.add(action.idempotency_key)
            return action
        obs_metrics.ACTION_RECONCILED.inc(outcome="refired")
        log.info("action_reconciled_refire", key=action.idempotency_key,
                 action_type=action.action_type.value)
        self._dispatch_one(action, handler)
        self.db.execution_result(action.idempotency_key,
                                 action.status.value,
                                 {"result": action.execution_result,
                                  "error": action.error_message,
                                  "reconciled": "refired"})
        self._executed_keys.add(action.idempotency_key)
        return action

    def _probe_landed(self, action: RemediationAction,
                      pre: dict) -> tuple[bool | None, dict | None]:
        """(landed, equivalent-result). landed=None means the probe could
        not decide (fail safe: no re-fire)."""
        ns = action.target_namespace
        at = action.action_type
        if self.settings.remediation_dry_run:
            return True, {"dry_run": True}
        try:
            if at == ActionType.SCALE_REPLICAS:
                deploys = self.backend.list_deployments(
                    ns, action.target_resource)
                if not deploys or "replicas" not in pre:
                    return None, None
                target = int(action.parameters.get(
                    "replicas", self._clamped(pre["replicas"] + 1)))
                if int(deploys[0].replicas) == target != int(pre["replicas"]):
                    return True, {"ok": True, "replicas": target,
                                  "prev_replicas": int(pre["replicas"])}
                return False, None
            if at == ActionType.CORDON_NODE:
                unsched = self._node_unschedulable(action.target_resource)
                if unsched is None:
                    return None, None
                if unsched and pre.get("unschedulable") is False:
                    return True, {"ok": True,
                                  "cordoned": action.target_resource}
                return (None, None) if pre.get("unschedulable") else \
                    (False, None)
            if at == ActionType.UNCORDON_NODE:
                unsched = self._node_unschedulable(action.target_resource)
                if unsched is None:
                    return None, None
                if not unsched and pre.get("unschedulable") is True:
                    return True, {"ok": True,
                                  "uncordoned": action.target_resource}
                return (None, None) if pre.get("unschedulable") is False \
                    else (False, None)
            if at == ActionType.ROLLBACK_DEPLOYMENT:
                deploys = self.backend.list_deployments(
                    ns, action.target_resource)
                if not deploys or "revision" not in pre:
                    return None, None
                if int(getattr(deploys[0], "revision", 0)) > pre["revision"]:
                    return True, {"ok": True,
                                  "rolled_back": action.target_resource}
                return False, None
            if at in RESTART_CLASS:
                # convergent: landed iff the previously-unhealthy pods
                # healed; a no-heal probe re-fires safely (deleting an
                # already-replaced pod is a no-op at the controller)
                pods = self.backend.list_pods(ns, action.target_resource)
                unhealthy = sorted(
                    p.name for p in pods
                    if not p.ready or p.waiting_reason or p.terminated_reason)
                if pre.get("unhealthy") and not unhealthy:
                    deleted = pre["unhealthy"][0]
                    if at == ActionType.RESTART_DEPLOYMENT:
                        return True, {"ok": True,
                                      "restarted": action.target_resource}
                    return True, {"ok": True, "deleted": deleted}
                return False, None
        except (AttributeError, KeyError, TypeError, ValueError) as exc:
            log.warning("reconcile_probe_failed", error=str(exc))
            return None, None
        return None, None

    # -- handlers ---------------------------------------------------------

    def _restart_pod(self, action: RemediationAction) -> dict:
        ns = action.target_namespace
        pods = self.backend.list_pods(ns, action.target_resource)
        if not pods:
            # target may be a pod name rather than a service
            ok = self.backend.delete_pod(ns, action.target_resource)
            return {"ok": ok, "deleted": action.target_resource if ok else None}
        unhealthy = [p for p in pods if not p.ready or p.waiting_reason
                     or p.terminated_reason]
        victim = (unhealthy or pods)[0]  # unhealthy-or-first (:86-134)
        ok = self.backend.delete_pod(ns, victim.name)
        return {"ok": ok, "deleted": victim.name}

    def _restart_deployment(self, action: RemediationAction) -> dict:
        ok = self.backend.restart_deployment(action.target_namespace,
                                             action.target_resource)
        return {"ok": ok, "restarted": action.target_resource}

    def _rollback_deployment(self, action: RemediationAction) -> dict:
        ok = self.backend.rollback_deployment(action.target_namespace,
                                              action.target_resource)
        return {"ok": ok, "rolled_back": action.target_resource}

    def _clamped(self, target: int) -> int:
        cap = max(int(getattr(self.settings,
                              "remediation_max_scale_replicas", 10)), 1)
        return min(int(target), cap)

    def _scale_target(self, action: RemediationAction) -> int | None:
        deploys = self.backend.list_deployments(action.target_namespace,
                                                action.target_resource)
        if not deploys:
            return None
        return int(action.parameters.get(
            "replicas", self._clamped(deploys[0].replicas + 1)))

    def _scale_replicas(self, action: RemediationAction) -> dict:
        ns = action.target_namespace
        deploys = self.backend.list_deployments(ns, action.target_resource)
        if not deploys:
            return {"ok": False, "error": "deployment not found"}
        prev = int(deploys[0].replicas)
        # default current+1 (:236-281), CLAMPED: an unbounded default let a
        # flapping workflow walk replicas upward one approved action at a
        # time. prev_replicas is recorded for saga compensation.
        target = int(action.parameters.get("replicas",
                                           self._clamped(prev + 1)))
        ok = self.backend.scale_deployment(ns, deploys[0].name, target)
        return {"ok": ok, "replicas": target, "prev_replicas": prev}

    def _cordon_node(self, action: RemediationAction) -> dict:
        ok = self.backend.cordon_node(action.target_resource)
        return {"ok": ok, "cordoned": action.target_resource}

    def _uncordon_node(self, action: RemediationAction) -> dict:
        ok = self.backend.uncordon_node(action.target_resource)
        return {"ok": ok, "uncordoned": action.target_resource}
