from .executor import RemediationExecutor
from .orchestrator import ACTION_RISKS, RemediationOrchestrator
from .verifier import RemediationVerifier

__all__ = [
    "ACTION_RISKS", "RemediationOrchestrator", "RemediationExecutor",
    "RemediationVerifier",
]
