from .compensator import RemediationCompensator
from .executor import RESTART_CLASS, RemediationExecutor
from .orchestrator import ACTION_RISKS, RemediationOrchestrator
from .verifier import RemediationVerifier

__all__ = [
    "ACTION_RISKS", "RESTART_CLASS", "RemediationOrchestrator",
    "RemediationExecutor", "RemediationCompensator", "RemediationVerifier",
]
