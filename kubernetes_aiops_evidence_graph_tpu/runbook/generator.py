"""Runbook generator.

Parity with the reference RunbookGenerator (generator.py:23-293): kubectl
command templates per action type, category-keyed investigation PromQL,
dashboard deep links, category-specific step additions, persisted runbook.
"""
from __future__ import annotations

from typing import Sequence

from ..models import Hypothesis, Incident, Runbook, RunbookStep


def evidence_detail_lines(evidence: Sequence[dict],
                          limit: int = 8) -> list[str]:
    """Human-review lines from anomalous pod evidence payloads — the
    per-container state / last-state / resource detail the reference
    records for operators (kubernetes_collector.py:203-267), surfaced in
    runbooks and tickets (VERDICT r4 item 7). Takes evidence DICTS (the
    workflow's journal-safe form; Evidence models dump to the same)."""
    lines: list[str] = []
    for ev in evidence:
        if ev.get("evidence_type") not in ("kubernetes_pod", "k8s_pod"):
            continue
        if not ev.get("is_anomaly"):
            continue
        data = ev.get("data") or {}
        for cs in data.get("container_statuses") or []:
            state = ""
            w = cs.get("waiting")
            if w and w.get("reason"):
                state = f" waiting={w['reason']}"
                if w.get("message"):
                    state += f" ({w['message']})"
            t = cs.get("terminated")
            if t and t.get("reason"):
                state += f" terminated={t['reason']} exit={t.get('exit_code')}"
            lt = cs.get("last_terminated")
            if lt and lt.get("reason"):
                state += (f" last-terminated={lt['reason']}"
                          f" exit={lt.get('exit_code')}")
            res = (data.get("resources") or {}).get(cs.get("name", ""), {})
            limits = res.get("limits")
            if limits:
                state += " limits=" + ",".join(
                    f"{k}={v}" for k, v in sorted(limits.items()))
            lines.append(
                f"pod {ev.get('entity_name', '?')}/{cs.get('name', 'app')}: "
                f"restarts={cs.get('restart_count', 0)}"
                f" ready={cs.get('ready')}" + state)
            if len(lines) >= limit:
                return lines
    return lines

_ACTION_COMMANDS: dict[str, list[str]] = {
    "rollback_deployment": [
        "kubectl rollout undo deployment/{service} -n {namespace}",
        "kubectl rollout status deployment/{service} -n {namespace}",
    ],
    "restart_deployment": [
        "kubectl rollout restart deployment/{service} -n {namespace}",
        "kubectl rollout status deployment/{service} -n {namespace}",
    ],
    "restart_pod": [
        "kubectl delete pod -l app={service} -n {namespace}",
        "kubectl get pods -l app={service} -n {namespace} -w",
    ],
    "scale_replicas": [
        "kubectl scale deployment/{service} -n {namespace} --replicas=<N>",
    ],
    "cordon_node": [
        "kubectl cordon <node>",
        "kubectl get pods -o wide -n {namespace} | grep <node>",
    ],
}

_INVESTIGATION_COMMANDS = [
    "kubectl describe pod -l app={service} -n {namespace}",
    "kubectl logs -l app={service} -n {namespace} --tail=200 --previous",
    "kubectl get events -n {namespace} --sort-by=.lastTimestamp | tail -30",
]

_CATEGORY_QUERIES: dict[str, list[str]] = {
    "resource_exhaustion": [
        'container_memory_working_set_bytes{{namespace="{namespace}",pod=~"{service}.*"}}',
        'increase(container_oom_events_total{{namespace="{namespace}"}}[1h])',
    ],
    "bad_deployment": [
        'kube_deployment_status_observed_generation{{namespace="{namespace}",deployment="{service}"}}',
        'rate(kube_pod_container_status_restarts_total{{namespace="{namespace}",pod=~"{service}.*"}}[15m])',
    ],
    "scaling_issue": [
        'kube_horizontalpodautoscaler_status_current_replicas{{namespace="{namespace}"}}',
        'histogram_quantile(0.99, sum(rate(http_request_duration_seconds_bucket{{service="{service}"}}[5m])) by (le))',
    ],
    "network_issue": [
        'sum(rate(http_requests_total{{namespace="{namespace}",service="{service}",code=~"5.."}}[5m]))',
    ],
    "infrastructure_issue": [
        'kube_node_status_condition{{condition="Ready",status="false"}}',
    ],
}

_CATEGORY_STEPS: dict[str, list[str]] = {
    "resource_exhaustion": ["Compare memory usage against limits; decide whether to raise limits or fix a leak"],
    "bad_deployment": ["Diff the last two revisions (images, env, config) before rolling back"],
    "configuration_error": ["Check ConfigMap/Secret references and volume mounts in the pod spec"],
    "infrastructure_issue": ["Check node conditions and consider cordoning before migrating pods"],
    "scaling_issue": ["Review HPA limits and resource requests before raising max replicas"],
    "network_issue": ["Test DNS and upstream connectivity from inside a debug pod"],
}


class RunbookGenerator:
    def __init__(self, grafana_url: str = "http://localhost:3000") -> None:
        self.grafana_url = grafana_url

    def generate(self, incident: Incident, hypothesis: Hypothesis,
                 evidence: Sequence[dict] = ()) -> Runbook:
        ctx = {"service": incident.service or "<service>",
               "namespace": incident.namespace}
        kubectl: list[str] = []
        for act in hypothesis.recommended_actions:
            for cmd in _ACTION_COMMANDS.get(act, ()):
                kubectl.append(cmd.format(**ctx))
        kubectl.extend(c.format(**ctx) for c in _INVESTIGATION_COMMANDS)

        queries = [q.format(**ctx)
                   for q in _CATEGORY_QUERIES.get(hypothesis.category.value, ())]

        steps = [
            RunbookStep(order=1, title="Confirm the hypothesis",
                        description=hypothesis.description,
                        commands=kubectl[:3]),
            RunbookStep(order=2, title="Investigate",
                        description="Gather context before acting",
                        commands=[c.format(**ctx) for c in _INVESTIGATION_COMMANDS]),
        ]
        extra = _CATEGORY_STEPS.get(hypothesis.category.value, [])
        for i, desc in enumerate(extra):
            steps.append(RunbookStep(order=3 + i, title="Category check", description=desc))
        detail = evidence_detail_lines(evidence)
        if detail:
            steps.append(RunbookStep(
                order=len(steps) + 1, title="Key evidence",
                description="Anomalous container state at collection time:\n"
                            + "\n".join(detail)))
        steps.append(RunbookStep(
            order=len(steps) + 1, title="Remediate",
            description="Execute the recommended action once confirmed",
            commands=kubectl[:2]))

        links = {
            "dashboard": f"{self.grafana_url}/d/aiops-overview",
            "logs": (f"{self.grafana_url}/explore?left="
                     f'{{"queries":[{{"expr":"{{namespace=\\"{incident.namespace}\\"}}"}}]}}'),
        }
        return Runbook(
            incident_id=incident.id,
            hypothesis_id=hypothesis.id,
            title=f"Runbook: {hypothesis.title} — {incident.service or incident.namespace}",
            summary=hypothesis.description,
            steps=steps,
            kubectl_commands=kubectl,
            investigation_queries=queries,
            dashboard_links=links,
            metadata={"category": hypothesis.category.value,
                      "rule_id": hypothesis.rule_id},
        )
