from .generator import RunbookGenerator

__all__ = ["RunbookGenerator"]
