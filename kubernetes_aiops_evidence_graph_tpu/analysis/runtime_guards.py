"""Pass 3 — runtime guards: transfer discipline + recompilation hazards.

Static passes can't see everything: whether the streaming paths actually
stay inside the REL_SLICE_BUCKETS retrace ladder under churn, and whether
the serving path really performs only explicit host transfers, are
runtime properties. These helpers are layered by the pytest fixture in
tests/test_graft_audit.py (marker ``static_audit``) around the
streaming-churn workload.

* :func:`no_implicit_transfers` — ``jax.transfer_guard`` context. On a
  real accelerator an implicit device→host sync (``.item()``, stray
  ``np.asarray``) raises; on the CPU backend transfers are free so the
  guard is a no-op — the AST host-sync rule is the backstop there.
* :class:`CompileCounter` — wraps jitted callables, tracking executable-
  cache growth AND the distinct static keys observed, so a test can
  assert compiles == distinct keys (no silent retrace) and that every
  key is drawn from the declared ladder.
* :class:`LockOrderGuard` — the dynamic half of the sentinel
  ``lock-order`` rule. Opt-in (``KAEG_LOCK_ORDER_GUARD=1``, installed by
  the tests/conftest.py session fixture): patches the
  ``threading.Lock``/``RLock`` factories so every lock created after
  install is tagged with its allocation site, records the
  site-level acquisition graph per thread, and flags any edge that
  closes a cycle — the two-thread deadlock shape, caught from a
  single-threaded witness. The chaos suites run under it in CI.
* :class:`CompileFence` — pass 5's runtime half. Opt-in
  (``KAEG_COMPILE_FENCE=1``, exported by the chaos CI jobs): hooks
  jax's backend-compile monitoring event and, inside an armed window
  (post-warm), attributes every compile to the enclosing
  :meth:`~CompileFence.region` label — any entry at all fails
  :meth:`~CompileFence.assert_clean`, which is the
  zero-post-warm-compile SLO observed rather than argued.
"""
from __future__ import annotations

import contextlib
import os
import sys
import threading
from dataclasses import dataclass, field


@contextlib.contextmanager
def no_implicit_transfers(device_to_host: bool = True,
                          host_to_device: bool = True):
    """Disallow implicit transfers in the wrapped block (explicit
    jax.device_get / device_put remain allowed). Serving paths that
    intentionally feed host-built delta arrays each tick guard only the
    device→host direction."""
    import jax
    with contextlib.ExitStack() as stack:
        if device_to_host:
            stack.enter_context(jax.transfer_guard_device_to_host("disallow"))
        if host_to_device:
            stack.enter_context(jax.transfer_guard_host_to_device("disallow"))
        yield


@dataclass
class CompileCounter:
    """Executable-cache watcher for one jitted callable.

    ``permitted`` is the retrace budget: the number of DISTINCT static
    keys the bucket ladders allow the workload to mint. ``over_budget``
    is the recompilation-hazard signal — more cache entries than distinct
    static keys means something non-static is leaking into the trace.
    """
    fn: "object"                       # the jitted callable (has _cache_size)
    static_argnames: tuple = ()
    baseline: int = 0
    keys_seen: set = field(default_factory=set)

    def __post_init__(self):
        self.baseline = self._cache_size()

    def _cache_size(self) -> int:
        try:
            return int(self.fn._cache_size())
        except Exception:  # graft-audit: allow[broad-except] private-API probe; counter degrades to key-only mode
            return 0

    def record(self, **static_kwargs) -> None:
        """Record one call's static key (call from a thin wrapper)."""
        key = tuple(sorted(
            (k, v if isinstance(v, (int, bool, str, tuple, type(None)))
             else repr(v))
            for k, v in static_kwargs.items()))
        self.keys_seen.add(key)

    @property
    def compiles(self) -> int:
        return self._cache_size() - self.baseline

    def over_budget(self, permitted: int) -> bool:
        return self.compiles > permitted

    def summary(self) -> dict:
        return {"compiles": self.compiles,
                "distinct_static_keys": len(self.keys_seen)}


def ladder_retrace_budget(delta_buckets, edge_buckets=None) -> int:
    """Upper bound on distinct static keys the delta ladders permit for
    one resident shape set (pk × ek combinations; offsets changes rebuild
    the resident state and are counted by the caller separately)."""
    pk = len(tuple(delta_buckets))
    ek = len(tuple(edge_buckets if edge_buckets is not None else delta_buckets))
    return pk * ek


class _GuardedLock:
    """Proxy around a real lock that reports acquire/release to the
    guard. Everything else (Condition's ``_is_owned`` etc.) delegates."""

    def __init__(self, guard: "LockOrderGuard", real, site: str):
        self._guard, self._real, self._site = guard, real, site

    def acquire(self, *a, **kw):
        got = self._real.acquire(*a, **kw)
        if got:
            self._guard._note_acquire(self._site)
        return got

    def release(self):
        self._guard._note_release(self._site)
        self._real.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, name):
        return getattr(self._real, name)


class LockOrderGuard:
    """Dynamic lock-ordering witness (the runtime half of the static
    sentinel ``lock-order`` rule).

    Locks are classed by ALLOCATION SITE (``file.py:line`` of the
    ``threading.Lock()`` call): every scorer's ``serve_lock`` is one
    class, every server's ``_lock`` another. Acquiring B while holding A
    records the site edge A→B; an acquisition whose new edge closes a
    cycle in the site graph is the deadlock shape — two threads walking
    the cycle in opposite directions can deadlock even if THIS run,
    single-threaded, sailed through. That is what makes the guard useful
    under the chaos suites: one interleaving witnesses the hazard for
    all of them.

    Test-only and opt-in: patches the ``threading.Lock``/``RLock``
    factories, so only locks created between :meth:`install` and
    :meth:`uninstall` are tracked. Violations collect in
    :attr:`violations`; :meth:`assert_clean` raises on any.
    """

    ENV = "KAEG_LOCK_ORDER_GUARD"

    def __init__(self):
        self.violations: list[dict] = []
        self._edges: set[tuple] = set()
        self._tls = threading.local()
        self._meta = threading.Lock()   # pre-patch factory: not tracked
        self._saved = None

    # -- factory patching ---------------------------------------------

    def _site(self) -> str:
        f = sys._getframe(2)
        here = (__file__, threading.__file__)
        while f is not None and f.f_code.co_filename in here:
            f = f.f_back
        if f is None:
            return "<unknown>"
        return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"

    def install(self) -> "LockOrderGuard":
        if self._saved is not None:
            return self
        real_lock, real_rlock = threading.Lock, threading.RLock
        self._saved = (real_lock, real_rlock)

        def lock_factory():
            return _GuardedLock(self, real_lock(), self._site())

        def rlock_factory():
            return _GuardedLock(self, real_rlock(), self._site())

        threading.Lock = lock_factory
        threading.RLock = rlock_factory
        return self

    def uninstall(self) -> None:
        if self._saved is not None:
            threading.Lock, threading.RLock = self._saved
            self._saved = None

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # -- acquisition bookkeeping --------------------------------------

    def _held(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _note_acquire(self, site: str) -> None:
        held = self._held()
        with self._meta:
            for h in held:
                if h == site:   # re-entrant same-class: not an ordering
                    continue
                if (h, site) not in self._edges and \
                        self._reaches(site, h):
                    self.violations.append({
                        "cycle": (h, site),
                        "thread": threading.current_thread().name,
                        "path": self._path(site, h),
                    })
                self._edges.add((h, site))
        held.append(site)

    def _note_release(self, site: str) -> None:
        held = self._held()
        if site in held:
            # remove the innermost matching frame (locks are released
            # LIFO in `with` blocks; tolerate hand-rolled ordering)
            for i in range(len(held) - 1, -1, -1):
                if held[i] == site:
                    del held[i]
                    break

    def _reaches(self, src: str, dst: str) -> bool:
        seen, todo = set(), [src]
        while todo:
            cur = todo.pop()
            if cur == dst:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            todo.extend(b for a, b in self._edges if a == cur)
        return False

    def _path(self, src: str, dst: str) -> list:
        """One witness path src→dst through the recorded edges."""
        parents, todo = {src: None}, [src]
        while todo:
            cur = todo.pop(0)
            if cur == dst:
                out = [cur]
                while parents[cur] is not None:
                    cur = parents[cur]
                    out.append(cur)
                return out[::-1]
            for a, b in self._edges:
                if a == cur and b not in parents:
                    parents[b] = cur
                    todo.append(b)
        return [src, dst]

    def assert_clean(self) -> None:
        if self.violations:
            raise AssertionError(
                f"lock-order cycles observed: {self.violations}")


def maybe_install_lock_order_guard() -> "LockOrderGuard | None":
    """Session hook: install iff ``KAEG_LOCK_ORDER_GUARD=1`` (how the
    chaos CI jobs and local chaos repros opt in)."""
    if os.environ.get(LockOrderGuard.ENV) != "1":
        return None
    return LockOrderGuard().install()


class CompileFence:
    """Pass 5's runtime half: attribute every post-warm XLA compile.

    The static lattice proves every serve-reachable variant HAS a warm
    path; the fence proves the warm paths actually pre-compile every
    executable the workload then requests — the property the
    zero-post-warm-compile SLO rests on, observed, not argued.

    Signal: jax's ``/jax/core/compile/backend_compile_duration``
    monitoring event, which fires once per backend compile and never on
    an executable-cache hit. jax 0.4.x has no per-listener unregister
    (only a global ``clear_event_listeners``), so the fence registers
    ONE module-level listener lazily and gates it on the active
    instance — install/uninstall flips the gate rather than touching
    jax's listener list, which keeps the fence composable with other
    monitoring users.

    Accounting is WINDOWED: compiles are only charged while the fence is
    armed (:meth:`armed` / :meth:`arm`/:meth:`disarm`), so cold-start
    and warm-path compiles — the legitimate ones — never count. Inside
    an armed window, :meth:`region` pushes a thread-local label (a
    lattice-point label, a test id) onto the attribution stack; a
    compile observed with no region on the stack is charged to
    ``"<unattributed>"``. The chaos CI jobs opt in with
    ``KAEG_COMPILE_FENCE=1`` (same discipline as the lock guard); the
    perf-contract test in tests/test_graft_lattice.py arms the fence
    after warm() and asserts :meth:`assert_clean` across the full tier
    × quant × shards × depth sweep, a forced mid-script rebuild, and an
    adopt_mesh heal.
    """

    ENV = "KAEG_COMPILE_FENCE"
    EVENT = "/jax/core/compile/backend_compile_duration"

    _listener_registered = False
    _active: "CompileFence | None" = None

    def __init__(self):
        self.violations: list[dict] = []
        self._armed = False
        self._tls = threading.local()
        self._meta = threading.Lock()

    # -- the one jax-side listener ------------------------------------

    @classmethod
    def _ensure_listener(cls) -> None:
        if cls._listener_registered:
            return
        import jax

        def _on_event(event: str, duration: float, **kw) -> None:
            fence = cls._active
            if fence is not None and event == cls.EVENT:
                fence._note_compile(duration)

        jax.monitoring.register_event_duration_secs_listener(_on_event)
        cls._listener_registered = True

    def install(self) -> "CompileFence":
        self._ensure_listener()
        type(self)._active = self
        return self

    def uninstall(self) -> None:
        if type(self)._active is self:
            type(self)._active = None

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # -- armed-window + attribution bookkeeping -----------------------

    def arm(self) -> None:
        """Start charging compiles (call AFTER the warm paths ran)."""
        self._armed = True

    def disarm(self) -> None:
        self._armed = False

    def _regions(self) -> list:
        stack = getattr(self._tls, "regions", None)
        if stack is None:
            stack = self._tls.regions = []
        return stack

    @contextlib.contextmanager
    def region(self, label: str):
        """Attribute compiles observed in this block to ``label``."""
        stack = self._regions()
        stack.append(label)
        try:
            yield self
        finally:
            stack.pop()

    def _note_compile(self, duration: float) -> None:
        if not self._armed:
            return
        stack = self._regions()
        label = stack[-1] if stack else "<unattributed>"
        with self._meta:
            self.violations.append({
                "region": label,
                "thread": threading.current_thread().name,
                "duration_secs": duration,
            })

    def assert_clean(self) -> None:
        if self.violations:
            regions = sorted({v["region"] for v in self.violations})
            raise AssertionError(
                f"{len(self.violations)} post-warm compile(s) observed "
                f"inside the fenced window (regions: {regions}): "
                f"{self.violations} — a serve-reachable variant was not "
                "pre-compiled by its declared warm path, or a retrace "
                "hazard minted a fresh executable")


def maybe_install_compile_fence() -> "CompileFence | None":
    """Session hook: install iff ``KAEG_COMPILE_FENCE=1`` (exported by
    the chaos CI jobs next to the lock guard). The fence installs
    DISARMED — suites arm it themselves after their warm phase, so
    opting a whole job in never misattributes legitimate cold
    compiles."""
    if os.environ.get(CompileFence.ENV) != "1":
        return None
    return CompileFence().install()
