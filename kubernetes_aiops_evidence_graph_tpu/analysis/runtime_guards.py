"""Pass 3 — runtime guards: transfer discipline + recompilation hazards.

Static passes can't see everything: whether the streaming paths actually
stay inside the REL_SLICE_BUCKETS retrace ladder under churn, and whether
the serving path really performs only explicit host transfers, are
runtime properties. These helpers are layered by the pytest fixture in
tests/test_graft_audit.py (marker ``static_audit``) around the
streaming-churn workload.

* :func:`no_implicit_transfers` — ``jax.transfer_guard`` context. On a
  real accelerator an implicit device→host sync (``.item()``, stray
  ``np.asarray``) raises; on the CPU backend transfers are free so the
  guard is a no-op — the AST host-sync rule is the backstop there.
* :class:`CompileCounter` — wraps jitted callables, tracking executable-
  cache growth AND the distinct static keys observed, so a test can
  assert compiles == distinct keys (no silent retrace) and that every
  key is drawn from the declared ladder.
"""
from __future__ import annotations

import contextlib
from dataclasses import dataclass, field


@contextlib.contextmanager
def no_implicit_transfers(device_to_host: bool = True,
                          host_to_device: bool = True):
    """Disallow implicit transfers in the wrapped block (explicit
    jax.device_get / device_put remain allowed). Serving paths that
    intentionally feed host-built delta arrays each tick guard only the
    device→host direction."""
    import jax
    with contextlib.ExitStack() as stack:
        if device_to_host:
            stack.enter_context(jax.transfer_guard_device_to_host("disallow"))
        if host_to_device:
            stack.enter_context(jax.transfer_guard_host_to_device("disallow"))
        yield


@dataclass
class CompileCounter:
    """Executable-cache watcher for one jitted callable.

    ``permitted`` is the retrace budget: the number of DISTINCT static
    keys the bucket ladders allow the workload to mint. ``over_budget``
    is the recompilation-hazard signal — more cache entries than distinct
    static keys means something non-static is leaking into the trace.
    """
    fn: "object"                       # the jitted callable (has _cache_size)
    static_argnames: tuple = ()
    baseline: int = 0
    keys_seen: set = field(default_factory=set)

    def __post_init__(self):
        self.baseline = self._cache_size()

    def _cache_size(self) -> int:
        try:
            return int(self.fn._cache_size())
        except Exception:  # graft-audit: allow[broad-except] private-API probe; counter degrades to key-only mode
            return 0

    def record(self, **static_kwargs) -> None:
        """Record one call's static key (call from a thin wrapper)."""
        key = tuple(sorted(
            (k, v if isinstance(v, (int, bool, str, tuple, type(None)))
             else repr(v))
            for k, v in static_kwargs.items()))
        self.keys_seen.add(key)

    @property
    def compiles(self) -> int:
        return self._cache_size() - self.baseline

    def over_budget(self, permitted: int) -> bool:
        return self.compiles > permitted

    def summary(self) -> dict:
        return {"compiles": self.compiles,
                "distinct_static_keys": len(self.keys_seen)}


def ladder_retrace_budget(delta_buckets, edge_buckets=None) -> int:
    """Upper bound on distinct static keys the delta ladders permit for
    one resident shape set (pk × ek combinations; offsets changes rebuild
    the resident state and are counted by the caller separately)."""
    pk = len(tuple(delta_buckets))
    ek = len(tuple(edge_buckets if edge_buckets is not None else delta_buckets))
    return pk * ek
