"""graft-sentinel rule family 1 — ``use-after-donate``.

A jitted call with ``donate_argnums`` hands the listed operand buffers to
XLA: after the call returns, those buffers may already hold the outputs
(the whole point of the resident-mirror tick discipline — zero
reallocation per dispatch). Reading, returning, or storing a donated
value afterwards is therefore a use-after-free in device memory; on CPU
it silently aliases, on TPU it is garbage. The sanctioned patterns are
(a) rebind the name from the call's outputs, or (b) pass a fresh
stand-in per call (see ``StreamingScorer.warm``).

The checker is an intraprocedural, flow-sensitive taint walk over every
function in the hot dirs:

* a call whose (trailing) name resolves to a donating callable taints
  each plain-``Name`` argument in a donated position;
* any later read of a tainted name — including inside a ``return`` or on
  the right-hand side of a store — on ANY path is a finding;
* reassignment clears the taint (fresh value, fresh buffer);
* branches fork the state and merge by union (tainted on any path is
  tainted), loop bodies run twice so a taint minted in iteration N is
  seen by the loop head in iteration N+1.

Donating callables come from two sources, both keyed to THIS file:
:data:`~.ast_lint.JIT_DECLARATIONS` entries for the file's relative path
with a non-empty donate tuple, and module-local jit sites (decorated
defs and ``name = jax.jit(fn, donate_argnums=...)`` assignments) — so
fixture trees exercise the rule without touching the central registry.

Scope limits (documented, deliberate): nested function definitions are
not descended into (closures over donated names are defined before the
donating call in every hot module), and only plain-``Name`` arguments
taint — attribute chains like ``self._features_dev`` are resident-state
handles whose rebinding the lock/tick discipline already owns.
"""
from __future__ import annotations

import ast

from .ast_lint import (JIT_DECLARATIONS, _call_name, _jit_decoration,
                       _static_argnames_from_call)


def _donating_callables(sf) -> dict[str, tuple[int, ...]]:
    donors: dict[str, tuple[int, ...]] = {}
    for (rel, fname), (_statics, donate) in JIT_DECLARATIONS.items():
        if rel == sf.rel and donate:
            donors[fname] = tuple(donate)
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.FunctionDef):
            dec = _jit_decoration(node)
            if dec is not None and dec[1]:
                donors[node.name] = tuple(dec[1])
        elif (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and _call_name(node.value) in ("jax.jit", "jit")):
            _statics, donate = _static_argnames_from_call(node.value)
            if donate:
                donors[node.targets[0].id] = tuple(donate)
    return donors


class _Taint:
    """One function's walk. ``state`` maps name -> (donor line, callee,
    donated position)."""

    def __init__(self, sf, donors: dict[str, tuple[int, ...]]):
        self.sf, self.donors = sf, donors
        self.seen: set[tuple[int, str]] = set()

    # -- statement execution ---------------------------------------------

    def run(self, fn: ast.FunctionDef) -> None:
        self.block(fn.body, {})

    def block(self, stmts, state: dict) -> dict:
        for stmt in stmts:
            state = self.stmt(stmt, state)
        return state

    def stmt(self, stmt, state: dict) -> dict:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return state                       # scope limit: not descended
        if isinstance(stmt, ast.If):
            out_b = self.block(stmt.body, dict(state))
            out_e = self.block(stmt.orelse, dict(state))
            return {**out_e, **out_b}          # union: tainted on any path
        if isinstance(stmt, (ast.For, ast.While)):
            if isinstance(stmt, ast.For):
                state = self.simple(stmt, state, reads_only=True)
            once = self.block(stmt.body, dict(state))
            twice = self.block(stmt.body, {**state, **once})
            merged = {**state, **twice}
            return self.block(stmt.orelse, merged)
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                self.check_reads(item.context_expr, state)
            state = self.kill_targets(stmt, state)
            return self.block(stmt.body, state)
        if isinstance(stmt, ast.Try):
            out_b = self.block(stmt.body, dict(state))
            merged = {**state, **out_b}
            for h in stmt.handlers:
                merged = {**merged, **self.block(h.body, dict(merged))}
            merged = self.block(stmt.orelse, merged)
            return self.block(stmt.finalbody, merged)
        return self.simple(stmt, state)

    def simple(self, stmt, state: dict, reads_only: bool = False) -> dict:
        self.check_reads(stmt, state)
        if reads_only:
            return state
        new = dict(state)
        for call in ast.walk(stmt):
            if not isinstance(call, ast.Call):
                continue
            callee = _call_name(call).rsplit(".", 1)[-1]
            donate = self.donors.get(callee)
            if not donate:
                continue
            for pos in donate:
                if pos < len(call.args) and isinstance(call.args[pos],
                                                       ast.Name):
                    new[call.args[pos].id] = (call.lineno, callee, pos)
        return self.kill_targets(stmt, new)

    # -- helpers ----------------------------------------------------------

    def check_reads(self, node, state: dict) -> None:
        if not state:
            return
        for n in ast.walk(node):
            if (isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
                    and n.id in state and (n.lineno, n.id) not in self.seen):
                dline, callee, pos = state[n.id]
                self.seen.add((n.lineno, n.id))
                self.sf.hit(
                    "use-after-donate", n.lineno,
                    f"'{n.id}' was passed in donated position {pos} of "
                    f"'{callee}' (line {dline}) and is read here — a "
                    "donated buffer is invalidated by XLA; rebind the "
                    "name from the call's outputs or pass a fresh "
                    "stand-in per call")

    @staticmethod
    def kill_targets(stmt, state: dict) -> dict:
        killed = set()
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.For):
            targets = [stmt.target]
        elif isinstance(stmt, ast.With):
            targets = [i.optional_vars for i in stmt.items
                       if i.optional_vars is not None]
        else:
            targets = []
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    killed.add(n.id)
        for n in ast.walk(stmt):
            if isinstance(n, ast.NamedExpr) and isinstance(n.target,
                                                           ast.Name):
                killed.add(n.target.id)
        if not killed:
            return state
        return {k: v for k, v in state.items() if k not in killed}


def check(sf) -> None:
    if not sf.in_hot:
        return
    donors = _donating_callables(sf)
    if not donors:
        return
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.FunctionDef):
            _Taint(sf, donors).run(node)
