"""Pass 2 — repo-specific AST lint over the package source (stdlib-only).

Rules (ids are what the waiver pragma names):

* ``tracer-branch``   — Python ``if``/``while`` on a non-static parameter
  inside jitted/traced code: the branch freezes one trace, silently
  specializing the kernel (or crashing with a ConcretizationError on
  device). ``x is None`` tests and shape/dtype attribute tests are static
  and exempt.
* ``np-in-traced``    — ``np.*`` calls inside jitted/traced code run on
  host per trace, constant-folding device data out of the jaxpr.
  ``pl.pallas_call`` kernel bodies count as traced code too (refs and
  scalars are traced values; np-in-traced / tracer-branch / wall-clock
  apply inside kernels).
* ``wall-clock``      — ``time.time()`` anywhere: NTP steps make it
  non-monotonic; durations must use monotonic()/perf_counter(). Epoch
  timestamps for export are waivable.
* ``host-sync``       — implicit device→host syncs in the hot modules
  (rca/, ops/, parallel/): ``float()``/``int()``/``np.asarray()``/
  ``.item()``/``.tolist()`` applied to device values. Explicit
  ``jax.device_get`` is the sanctioned transfer and exempts the
  expression.
* ``broad-except``    — ``except Exception``/bare except that swallows
  (handlers that re-raise are exempt). Intentional isolation boundaries
  carry a waiver with the reason.
* ``missing-static``  — an ``int``/``bool``-annotated parameter of a
  jitted function not listed in static_argnames: it would be traced and
  either retrace per value or break Python-side use.
* ``jit-undeclared``/``jit-signature`` — every jit site in the hot
  modules must be declared in :data:`JIT_DECLARATIONS` with its exact
  static_argnames and donate_argnums (completeness: a new jitted kernel
  must register its signature — and its jaxpr entrypoint — to land).
* ``tick-donation``   — a resident-state tick entrypoint (a jit site
  named ``tick`` or ``*_tick`` under the hot dirs) that donates no
  buffers: the tick applies per-dispatch deltas to device-resident
  mirror state, so un-donated state means XLA reallocates the full
  mirror every tick (and a pipelined executor holds depth+1 copies live
  in HBM). The exact donated positions are pinned by
  :data:`JIT_DECLARATIONS`; this rule catches the class.
* ``recovery-no-broad-except`` — a broad except inside a RECOVERY
  function (name matching recover/degrad/fallback/quarantine/watchdog/
  escalat under the hot dirs) that neither re-raises nor escalates (a
  call whose name contains ``escalat``): a degradation path that
  swallows errors turns a non-transient fault into silent wrong-tier
  serving — the one place broad-except may NOT be waived into silence
  (graft-shield). In recovery context this rule replaces the generic
  ``broad-except``; handlers that escalate are the sanctioned pattern
  and produce no finding.

Waiver pragma: ``# graft-audit: allow[rule] reason`` on the offending
line or the line above. Waived sites are counted and reported, never
silently dropped.
"""
from __future__ import annotations

import ast
import re
from pathlib import Path

from .findings import Finding, Report

HOT_DIRS = {"rca", "ops", "parallel", "learn"}

# functions that run under trace without their own jit decoration (called
# from jitted entrypoints in the hot modules) — tracer-branch and
# np-in-traced apply inside them too
TRACED_EXTRA = {
    "forward", "loss_fn", "rel_messages", "_message_pass",
    "_message_pass_bucketed", "gather_matmul_segment",
    "pallas_gather_matmul_segment", "scatter_add",
    "scatter_max", "scatter_add_2d", "gather_neighbors", "_aggregate",
    "finish_scores", "pair_contract", "_ring_messages", "_ring_readout",
    "local_loss", "local_score", "local_tick",
    "evidence_fold_block", "local_rules_tick", "local_gnn_tick",
    "_assemble_ring", "_readout_ring",
}

# calls that produce device values (for the host-sync dataflow)
DEVICE_RETURNING = {
    "forward_batch", "gather_matmul_segment",
    "pallas_gather_matmul_segment", "k_hop_reach",
    "propagate_labels", "segment_sum", "scatter_add", "scatter_max",
}
# explicit-transfer calls: an expression containing one is sanctioned
SAFE_TRANSFER = {"jax.device_get", "jax.device_put", "jax.block_until_ready"}
# jax.* calls that return host objects, not device arrays
NON_ARRAY_JAX = {
    "jax.devices", "jax.local_devices", "jax.device_count",
    "jax.local_device_count", "jax.process_index", "jax.process_count",
    "jax.default_backend", "jax.tree_util.tree_structure",
}
HOST_SINKS = {"float", "int", "bool"}
NP_SINKS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
SYNC_METHODS = {"item", "tolist"}

# (posix path relative to the package root, function name) -> (expected
# static_argnames, expected donate_argnums). EVERY jit site under a hot
# dir must appear here — jaxpr-audit registration rides along (see
# registry.py module docstring).
JIT_DECLARATIONS: dict[tuple[str, str], tuple[tuple[str, ...], tuple[int, ...]]] = {
    ("rca/gnn.py", "step"): (("rel_offsets", "slices_sorted"), (0, 1)),
    ("rca/gnn.py", "forward"): (
        ("sorted_by_dst", "rel_offsets", "slices_sorted", "compute_dtype",
         "pallas"),
        ()),
    ("rca/gnn_streaming.py", "_gnn_tick"): (
        ("pk", "ek", "pi", "rel_offsets", "slices_sorted", "compute_dtype",
         "pallas"),
        (2, 3, 4, 5, 6, 7)),
    # graft-fuse: the fused streaming tick — same donation contract as
    # _gnn_tick (the resident mirror flows through the one Pallas
    # kernel's aliased outputs, never reallocates); graft-tide adds the
    # bf16 compute static
    ("rca/gnn_streaming.py", "_gnn_fused_tick"): (
        ("pk", "ek", "pi", "rel_offsets", "compute_dtype"),
        (2, 3, 4, 5, 6, 7)),
    # graft-tide: the beyond-VMEM DMA streaming tick — the donated set
    # grows by the two persistent [N, H] activation ping-pong buffers
    # (positions 9/10), rebound from the outputs every tick; features
    # (position 1) stays read-only on the f32 path
    ("rca/gnn_streaming.py", "_gnn_dma_tick"): (
        ("pk", "ek", "pi", "rel_offsets", "node_block", "compute_dtype"),
        (2, 3, 4, 5, 6, 7, 9, 10)),
    # graft-tide quantized tiers: the HBM-resident bf16/int8 feature
    # table (position 1) is part of the resident mirror — donated and
    # rebound through the kernel's aliased output like the edge arrays
    ("rca/gnn_streaming.py", "_gnn_dma_tick_q"): (
        ("pk", "ek", "pi", "rel_offsets", "node_block", "compute_dtype",
         "feat_quant"),
        (1, 2, 3, 4, 5, 6, 7, 9, 10)),
    # graft-shield snapshot kernels: pack/unpack the resident state into
    # ONE int32 transfer (no donation — the resident buffers must survive
    # the snapshot; registered jaxpr entrypoints with zero-collective cost)
    ("rca/shield.py", "_snapshot_pack"): ((), ()),
    ("rca/shield.py", "_snapshot_unpack"): (("layout",), ()),
    # graft-heal per-shard attestation fold (no donation — the resident
    # arrays must survive the checksum; registered jaxpr entrypoint
    # heal.attest_fold with zero-collective cost)
    ("rca/heal.py", "attest_fold"): (("shards",), ()),
    ("rca/streaming.py", "_tick"): (
        ("padded_incidents", "pair_width", "pk", "rk", "width"),
        (0, 3, 4, 5)),
    # graft-intake: the columnar staged-slab split (no donation — the
    # slab is a host staging buffer, the outputs feed the tick's
    # NON-donated ints/rows operands; registered jaxpr entrypoint
    # ingest.delta_pack with zero-collective cost)
    ("rca/streaming.py", "_delta_pack"): (("li", "pk", "dim", "gi"), ()),
    # graft-fleet mesh-resident ticks (parallel/sharded_streaming.py):
    # same donation contract as their single-device counterparts — the
    # sharded resident mirror flows through, never reallocates
    ("parallel/sharded_streaming.py", "rules_tick"): ((), (0, 3, 4, 5)),
    ("parallel/sharded_streaming.py", "gnn_tick"): ((), (2, 3, 4, 5, 6, 7)),
    ("rca/tpu_backend.py", "_score_device"): (
        ("padded_incidents", "pair_width"), ()),
    ("rca/device_metrics.py", "_scan_stream"): (("k",), ()),
    ("rca/device_metrics.py", "_scan_matmul"): (("k",), ()),
    ("rca/device_metrics.py", "<lambda>"): ((), ()),
    ("rca/device_metrics.py", "_loop_score"): (
        ("padded_incidents", "pair_width"), ()),
    ("rca/device_metrics.py", "scan_fwd"): (
        ("k", "sorted_", "offs", "ss", "cd", "pal"), ()),
    ("ops/propagate.py", "k_hop_reach"): (("num_nodes", "hops"), ()),
    ("ops/propagate.py", "propagate_labels"): (
        ("num_nodes", "iterations"), ()),
    ("parallel/sharded_gnn.py", "step"): ((), (0, 1)),
    ("parallel/sharded_rules.py", "sharded"): ((), ()),
    # graft-evolve fine-tune step (learn/trainer.py): same donation
    # discipline as the offline step — params/opt_state consumed and
    # rebound every step; the anchor (the serving checkpoint) is READ
    # every step and must NOT be donated
    ("learn/trainer.py", "step"): (("rel_offsets", "slices_sorted"),
                                   (0, 1)),
}

_WAIVER_RE = re.compile(
    r"#\s*graft-audit:\s*allow\[([a-zA-Z0-9_,\- ]+)\]\s*(.*)")

# functions whose broad excepts fall under the stricter
# recovery-no-broad-except contract (graft-shield)
_RECOVERY_FN_RE = re.compile(
    r"recover|degrad|fallback|quarantine|watchdog|escalat")


def _dotted(node) -> str:
    """'a.b.c' for Name/Attribute chains, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _call_name(call: ast.Call) -> str:
    return _dotted(call.func)


def _is_device_call(name: str) -> bool:
    if not name:
        return False
    if name in SAFE_TRANSFER or name in NON_ARRAY_JAX:
        return False
    if name.startswith("jnp.") or name.startswith("jax."):
        return True
    return name.rsplit(".", 1)[-1] in DEVICE_RETURNING


def _expr_transfer_kind(expr, device_names: set[str]) -> str:
    """'safe' (contains an explicit transfer), 'device', or 'host'."""
    has_device = False
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            name = _call_name(n)
            if name in SAFE_TRANSFER:
                return "safe"
            if _is_device_call(name):
                has_device = True
        elif isinstance(n, ast.Name) and n.id in device_names:
            has_device = True
    return "device" if has_device else "host"


def _static_argnames_from_call(call: ast.Call) -> tuple[set[str], tuple[int, ...]]:
    statics: set[str] = set()
    donate: tuple[int, ...] = ()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                statics.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                statics.update(e.value for e in v.elts
                               if isinstance(e, ast.Constant))
        elif kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                donate = (v.value,)
            elif isinstance(v, (ast.Tuple, ast.List)):
                donate = tuple(e.value for e in v.elts
                               if isinstance(e, ast.Constant))
    return statics, donate


def _jit_decoration(fn: ast.FunctionDef):
    """(statics, donate) if fn is jit-decorated, else None."""
    for dec in fn.decorator_list:
        name = _dotted(dec) if not isinstance(dec, ast.Call) \
            else _call_name(dec)
        if isinstance(dec, ast.Call):
            if name in ("jax.jit", "jit"):
                return _static_argnames_from_call(dec)
            if name in ("partial", "functools.partial") and dec.args:
                inner = _dotted(dec.args[0])
                if inner in ("jax.jit", "jit"):
                    return _static_argnames_from_call(dec)
        elif name in ("jax.jit", "jit"):
            return set(), ()
    return None


class _FileLint:
    def __init__(self, path: Path, rel: str, source: str):
        self.path, self.rel, self.source = path, rel, source
        self.tree = ast.parse(source)
        self.findings: list[Finding] = []
        self.in_hot = bool(set(Path(rel).parts[:-1]) & HOT_DIRS)
        self.waivers: dict[int, tuple[set[str], str]] = {}
        for i, line in enumerate(source.splitlines(), start=1):
            m = _WAIVER_RE.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")}
                self.waivers[i] = (rules, m.group(2).strip())
        # jit call-form targets in this module: jax.jit(fn_name, ...)
        self.call_form_jits: dict[str, tuple[set[str], tuple[int, ...], int]] = {}
        # functions handed to pl.pallas_call as the kernel body: traced
        # code (refs and scalars are traced values), so the np-in-traced /
        # tracer-branch rules apply inside them
        self.pallas_kernels: set[str] = set()
        for n in ast.walk(self.tree):
            if not isinstance(n, ast.Call):
                continue
            name = _call_name(n)
            if name in ("jax.jit", "jit"):
                statics, donate = _static_argnames_from_call(n)
                if n.args and isinstance(n.args[0], ast.Name):
                    self.call_form_jits[n.args[0].id] = (statics, donate,
                                                         n.lineno)
                elif n.args and isinstance(n.args[0], ast.Lambda):
                    self.call_form_jits["<lambda>"] = (statics, donate,
                                                       n.lineno)
            elif name in ("pl.pallas_call", "pallas_call",
                          "pltpu.pallas_call"):
                if n.args and isinstance(n.args[0], ast.Name):
                    self.pallas_kernels.add(n.args[0].id)

    def hit(self, rule: str, line: int, message: str) -> None:
        waived, reason = False, ""
        for ln in (line, line - 1):
            w = self.waivers.get(ln)
            if w and (rule in w[0] or "all" in w[0]):
                waived, reason = True, w[1]
                break
        self.findings.append(Finding(
            rule=rule, where=f"{self.rel}:{line}", message=message,
            pass_name="ast", waived=waived, waiver_reason=reason))

    # -- rules -----------------------------------------------------------

    def lint(self, check_jit_declarations: bool) -> list[Finding]:
        self._broad_except()
        self._wall_clock()
        traced = self._traced_functions()
        for fn, statics in traced:
            self._tracer_branch(fn, statics)
            self._np_in_traced(fn)
        if self.in_hot:
            self._host_sync()
            self._missing_static(traced)
            self._tick_donation()
            if check_jit_declarations:
                self._jit_declarations()
        return self.findings

    def _broad_except(self) -> None:
        self._visit_excepts(self.tree, "")

    def _visit_excepts(self, node, fname: str) -> None:
        """Walk handlers tracking the innermost enclosing function name —
        recovery-named functions get the stricter rule."""
        for child in ast.iter_child_nodes(node):
            nf = child.name if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)) else fname
            if isinstance(child, ast.ExceptHandler):
                self._check_except(child, fname)
            self._visit_excepts(child, nf)

    def _check_except(self, n: ast.ExceptHandler, fname: str) -> None:
        t = n.type
        broad = t is None or (isinstance(t, ast.Name)
                              and t.id in ("Exception", "BaseException"))
        if not broad:
            return
        reraises = any(isinstance(b, ast.Raise) for b in ast.walk(n))
        if self.in_hot and _RECOVERY_FN_RE.search(fname or ""):
            # recovery context (graft-shield): swallowing is never an
            # isolation boundary here — the handler must re-raise or
            # escalate to the next degradation tier
            escalates = any(
                isinstance(b, ast.Call)
                and "escalat" in _call_name(b).rsplit(".", 1)[-1]
                for b in ast.walk(n))
            if not (reraises or escalates):
                self.hit("recovery-no-broad-except", n.lineno,
                         f"broad except in recovery function '{fname}' "
                         "neither re-raises nor escalates: a degradation "
                         "path that swallows turns non-transient faults "
                         "into silent wrong-tier serving")
            return
        if reraises:
            return   # catch-and-rethrow is instrumentation, not swallowing
        self.hit("broad-except", n.lineno,
                 "broad except swallows all errors; narrow the catch "
                 "or waive with the isolation reason")

    def _wall_clock(self) -> None:
        for n in ast.walk(self.tree):
            if isinstance(n, ast.Call) and _call_name(n) == "time.time":
                self.hit("wall-clock", n.lineno,
                         "time.time() is not monotonic under NTP steps; "
                         "use time.monotonic()/perf_counter() for durations")

    def _traced_functions(self):
        out = []
        for n in ast.walk(self.tree):
            if not isinstance(n, ast.FunctionDef):
                continue
            dec = _jit_decoration(n)
            if dec is not None:
                out.append((n, dec[0]))
            elif n.name in self.call_form_jits:
                out.append((n, self.call_form_jits[n.name][0]))
            elif n.name in self.pallas_kernels:
                # pallas kernel bodies are traced wherever they live
                out.append((n, self._annotated_static_params(n)))
            elif self.in_hot and n.name in TRACED_EXTRA:
                # statics by convention: int/bool-annotated params
                out.append((n, self._annotated_static_params(n)))
        return out

    @staticmethod
    def _annotated_static_params(fn: ast.FunctionDef) -> set[str]:
        statics = set()
        for a in list(fn.args.args) + list(fn.args.kwonlyargs):
            ann = a.annotation
            if isinstance(ann, ast.Name) and ann.id in ("int", "bool", "str"):
                statics.add(a.arg)
        return statics

    def _tracer_branch(self, fn: ast.FunctionDef, statics: set[str]) -> None:
        params = {a.arg for a in list(fn.args.args) + list(fn.args.kwonlyargs)}
        tracers = params - statics - self._annotated_static_params(fn)
        for n in ast.walk(fn):
            if not isinstance(n, (ast.If, ast.While)):
                continue
            if self._test_branches_on(n.test, tracers):
                self.hit("tracer-branch", n.lineno,
                         "Python branch on a traced value inside jitted "
                         "code freezes one trace per call site; use "
                         "jnp.where/lax.cond or make the argument static")

    @staticmethod
    def _test_branches_on(test, tracers: set[str]) -> bool:
        exempt_roots = set()
        parents: dict[int, ast.AST] = {}
        for node in ast.walk(test):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node
            if isinstance(node, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                exempt_roots.add(id(node))          # `x is (not) None`
        for node in ast.walk(test):
            if not (isinstance(node, ast.Name) and node.id in tracers):
                continue
            # climb: exempt if under an is/is-not compare or behind an
            # attribute access (x.ndim / x.shape — static under trace)
            cur, under_attr = node, False
            while cur is not None:
                if id(cur) in exempt_roots:
                    under_attr = True
                    break
                p = parents.get(id(cur))
                if isinstance(p, ast.Attribute) and p.value is cur:
                    under_attr = True
                    break
                cur = p
            if not under_attr:
                return True
        return False

    def _np_in_traced(self, fn: ast.FunctionDef) -> None:
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            name = _call_name(n)
            if name.startswith("np.") or name.startswith("numpy."):
                self.hit("np-in-traced", n.lineno,
                         f"{name}() inside traced code runs on host per "
                         "trace and constant-folds device data")

    @staticmethod
    def _scope_walk(stmt):
        """Walk one statement without descending into nested function
        scopes (each scope tracks its own device-value names)."""
        yield stmt
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        for child in ast.iter_child_nodes(stmt):
            yield from _FileLint._scope_walk(child)

    def _host_sync(self) -> None:
        scopes = [s for s in ast.walk(self.tree)
                  if isinstance(s, (ast.Module, ast.FunctionDef,
                                    ast.AsyncFunctionDef))]
        for scope in scopes:
            device_names: set[str] = set()
            for stmt in scope.body:
                for n in self._scope_walk(stmt):
                    if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                            and isinstance(n.targets[0], ast.Name):
                        kind = _expr_transfer_kind(n.value, device_names)
                        tgt = n.targets[0].id
                        if kind == "device":
                            device_names.add(tgt)
                        else:
                            device_names.discard(tgt)
                    elif isinstance(n, ast.Call):
                        self._check_sync_call(n, device_names)

    def _check_sync_call(self, n: ast.Call, device_names: set[str]) -> None:
        name = _call_name(n)
        if name in HOST_SINKS or name in NP_SINKS:
            for arg in n.args:
                if _expr_transfer_kind(arg, device_names) == "device":
                    self.hit("host-sync", n.lineno,
                             f"{name}() on a device value is an implicit "
                             "device->host sync; fetch once with "
                             "jax.device_get")
                    return
        if isinstance(n.func, ast.Attribute) and n.func.attr in SYNC_METHODS:
            if _expr_transfer_kind(n.func.value, device_names) == "device":
                self.hit("host-sync", n.lineno,
                         f".{n.func.attr}() on a device value is an "
                         "implicit device->host sync; fetch once with "
                         "jax.device_get")

    def _missing_static(self, traced) -> None:
        for fn, statics in traced:
            if _jit_decoration(fn) is None \
                    and fn.name not in self.call_form_jits:
                continue       # convention-traced helpers: no jit signature
            for a in list(fn.args.args) + list(fn.args.kwonlyargs):
                ann = a.annotation
                if isinstance(ann, ast.Name) and ann.id in ("int", "bool") \
                        and a.arg not in statics:
                    self.hit("missing-static", fn.lineno,
                             f"parameter '{a.arg}: {ann.id}' of jitted "
                             f"'{fn.name}' is not in static_argnames — it "
                             "will be traced (retrace per value or "
                             "ConcretizationError)")

    def _jit_sites(self) -> list[tuple[str, set, tuple, int]]:
        """Every jit site in this module: decorated defs + call-form."""
        sites: list[tuple[str, set, tuple, int]] = []
        for n in ast.walk(self.tree):
            if isinstance(n, ast.FunctionDef):
                dec = _jit_decoration(n)
                if dec is not None:
                    sites.append((n.name, dec[0], dec[1], n.lineno))
        for fname, (statics, donate, lineno) in self.call_form_jits.items():
            sites.append((fname, statics, donate, lineno))
        return sites

    def _tick_donation(self) -> None:
        """Resident-state tick entrypoints must donate their mirror state
        (graft-pipeline): a tick named ``tick``/``*_tick`` with an empty
        donate_argnums reallocates the full resident set every dispatch."""
        for fname, _statics, donate, lineno in self._jit_sites():
            if fname != "tick" and not fname.endswith("_tick"):
                continue
            if not tuple(donate):
                self.hit("tick-donation", lineno,
                         f"tick entrypoint '{fname}' donates no buffers — "
                         "the resident mirror state it updates must flow "
                         "through donate_argnums or every tick reallocates "
                         "it (exact positions are pinned in "
                         "JIT_DECLARATIONS)")

    def _jit_declarations(self) -> None:
        for fname, statics, donate, lineno in self._jit_sites():
            declared = JIT_DECLARATIONS.get((self.rel, fname))
            if declared is None:
                self.hit("jit-undeclared", lineno,
                         f"jit site '{fname}' is not declared in "
                         "analysis.ast_lint.JIT_DECLARATIONS — register "
                         "its static/donate signature (and a jaxpr-audit "
                         "entrypoint if it is a hot kernel)")
                continue
            want_statics, want_donate = set(declared[0]), tuple(declared[1])
            if statics != want_statics or tuple(donate) != want_donate:
                self.hit("jit-signature", lineno,
                         f"jit site '{fname}' signature drifted: "
                         f"static_argnames={sorted(statics)} "
                         f"donate_argnums={tuple(donate)} declared "
                         f"{sorted(want_statics)}/{want_donate}")


def package_root() -> Path:
    return Path(__file__).resolve().parent.parent


def lint_tree(root: "Path | str | None" = None,
              check_jit_declarations: "bool | None" = None) -> Report:
    """Lint every .py under ``root`` (default: the installed package).
    ``check_jit_declarations`` defaults to on only for the installed
    package (fixture trees are not in JIT_DECLARATIONS); fixtures that
    seed a ``jit-undeclared`` finding pass True explicitly."""
    base = Path(root) if root is not None else package_root()
    check_decls = (root is None if check_jit_declarations is None
                   else check_jit_declarations)
    report = Report()
    for path in sorted(base.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel = path.relative_to(base).as_posix()
        try:
            lint = _FileLint(path, rel, path.read_text())
        except SyntaxError as exc:
            report.findings.append(Finding(
                rule="syntax-error", where=f"{rel}:{exc.lineno or 0}",
                message=str(exc), pass_name="ast"))
            continue
        report.findings.extend(lint.lint(check_decls))
    return report
