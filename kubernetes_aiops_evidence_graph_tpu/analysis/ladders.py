"""Pass 5 (graft-lattice), ladder half: the ONE declared registry of
every bucket ladder in the tree, plus the static contracts that keep the
compile surface discrete.

Every static shape the serving stack compiles is drawn from a bucket
ladder — the delta/row churn ladders, the relation-slice capacity
ladder, the node/edge/incident snapshot ladders, the evidence
slot-width and pair-width ladders, the multi-tenant pack ladder, and
the DMA node-block quantum. Before this module those rungs were
re-declared across rca/streaming.py, rca/tpu_backend.py,
graph/snapshot.py, ops/pallas_segment.py, config/settings.py,
parallel/partition.py and analysis/registry.py; a one-sided edit (a
rung added to the serving ladder but not the bench ladder, a capacity
that stops dividing EDGE_TILE) silently mints mid-serve compiles or
mis-tiled kernels. Now the defining modules IMPORT these constants
(the drift-guard test in tests/test_graft_lattice.py pins the
identity), and the checks below run in the stdlib-only fast audit:

* ``ladder-gap``   — a ladder must be strictly increasing, its
  consecutive-rung ratio bounded (worst-case padding inflation), and
  its top rung must either cover the declared 500k-pod scale target or
  declare a reachable above-ladder escalation (the rebuild path, the
  ``_REL_SLICE_STEP`` rounding rule) — a ladder that just *ends* below
  its workload turns bucket overflow into an unplanned off-ladder
  compile mid-serve.
* ``ladder-divisibility`` — tiling/sharding quanta must divide every
  capacity drawn from the ladder: EDGE_TILE divides every relation-
  slice rung AND the above-ladder step (tiles never straddle a slice),
  and the DMA node-block quantum aligns with every node rung
  (``pn % min(node_block, pn) == 0`` — rungs at or above the block are
  block-multiples, smaller rungs divide the block).

Fixture trees declare ladders inline with a module-level literal::

    GRAFT_LADDERS = {
        "my_ladder": {"rungs": [64, 256], "max_gap_ratio": 4.0,
                      "covers": 500, "escalation": "none",
                      "divisor": 64, "step": 0},
    }

This module is stdlib-only (never imports jax, numpy or the package
runtime) so ``scripts/audit-fast.sh`` stays a seconds-scale loop and so
the hot modules can import the rungs without an import cycle.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass
from pathlib import Path

from .findings import Finding, Report

# -- canonical rungs ---------------------------------------------------------
# The single source of truth. Defining modules import these (aliased to
# their historical private names); values are byte-for-byte the ladders
# the serving stack compiled before the dedupe — no static shape, jit
# cache key or cost baseline moves.

# scale target the topology ladders must reach (graft-tide stretched the
# node/edge rungs for 500k-pod configs; the coverage check pins it)
MAX_PODS = 500_000

# streaming churn ladders (rca/streaming.py): feature-delta rows and
# evidence-row-delta rows per tick
DELTA_BUCKETS = (64, 256, 1024, 4096, 16384, 65536)
ROW_BUCKETS = (4, 16, 64, 256)

# snapshot-path edge ladder (rca/tpu_backend.py)
EDGE_BUCKETS = (256, 1024, 4096, 16384, 65536, 262144)
# dense evidence slot-width / pair-width ladders (rca/tpu_backend.py)
WIDTH_BUCKETS = (16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192, 16384)
PAIR_WIDTH_BUCKETS = (4, 8, 16, 32, 64, 128, 256, 512, 1024)
# multi-tenant packed-incident ladder (rca/tpu_backend.py, graft-surge)
PACK_BUCKETS = (8, 32, 128, 512, 2048)

# relation-slice capacity ladder + above-ladder rounding step
# (graph/snapshot.py; shared by build_snapshot, parallel/partition.py
# and the streaming edge mirror)
REL_SLICE_BUCKETS = (64, 128, 256, 512, 1024, 2048, 4096, 8192,
                     16384, 24576, 32768)
REL_SLICE_STEP = 8192

# snapshot topology ladders (config/settings.py defaults; graft-tide
# stretched node/edge rungs to 500k-pod scale)
NODE_BUCKET_SIZES = (256, 1024, 4096, 16384, 65536, 262144, 524288)
EDGE_BUCKET_SIZES = (1024, 4096, 16384, 65536, 262144, 1048576, 4194304)
INCIDENT_BUCKET_SIZES = (8, 32, 128, 512)

# kernel tiling quanta: edge rows per Pallas grid step
# (ops/pallas_segment.py) and the DMA streaming node-block
# (analysis/registry.py / settings.gnn_dma_node_block default)
EDGE_TILE = 64
DMA_NODE_BLOCK = 2048


@dataclass(frozen=True)
class Ladder:
    """One declared bucket ladder and its contracts.

    ``escalation`` names the above-ladder path: ``"rebuild"`` (bucket
    overflow escalates to the store-derived rebuild — NeedsRebuild),
    ``"step"`` (counts beyond the top rung round to ``step`` multiples,
    the rel_slice_offsets rule), or ``"none"`` (the top rung must cover
    ``covers`` outright). ``divisor`` must divide every rung;
    ``divisor_min`` relaxes it to the ``min(divisor, rung)`` alignment
    rule the DMA dispatcher actually checks."""
    name: str
    rungs: tuple
    defined_in: str               # "module.py:ATTR" provenance
    max_gap_ratio: float = 4.0
    covers: int = 0               # 0 = no coverage target
    escalation: str = "none"      # "rebuild" | "step" | "none"
    step: int = 0                 # above-ladder rounding (escalation="step")
    divisor: int = 0              # 0 = no divisibility contract
    divisor_min: bool = False     # min(divisor, rung) alignment semantics


# the declared registry — every ladder in the tree, with its contracts
LADDERS: tuple[Ladder, ...] = (
    Ladder("delta", DELTA_BUCKETS, "rca/streaming.py:_DELTA_BUCKETS",
           covers=MAX_PODS, escalation="rebuild"),
    Ladder("row", ROW_BUCKETS, "rca/streaming.py:_ROW_BUCKETS",
           escalation="rebuild"),
    Ladder("edge", EDGE_BUCKETS, "rca/tpu_backend.py:_EDGE_BUCKETS",
           escalation="rebuild"),
    Ladder("width", WIDTH_BUCKETS, "rca/tpu_backend.py:_WIDTH_BUCKETS",
           max_gap_ratio=2.0, escalation="rebuild"),
    Ladder("pair_width", PAIR_WIDTH_BUCKETS,
           "rca/tpu_backend.py:_PAIR_WIDTH_BUCKETS",
           max_gap_ratio=2.0, escalation="rebuild"),
    Ladder("pack", PACK_BUCKETS, "rca/tpu_backend.py:_PACK_BUCKETS",
           escalation="rebuild"),
    Ladder("rel_slice", REL_SLICE_BUCKETS,
           "graph/snapshot.py:REL_SLICE_BUCKETS",
           max_gap_ratio=2.0, covers=8 * MAX_PODS, escalation="step",
           step=REL_SLICE_STEP, divisor=EDGE_TILE),
    Ladder("node", NODE_BUCKET_SIZES,
           "config/settings.py:node_bucket_sizes",
           covers=MAX_PODS, divisor=DMA_NODE_BLOCK, divisor_min=True),
    Ladder("edge_snapshot", EDGE_BUCKET_SIZES,
           "config/settings.py:edge_bucket_sizes", covers=8 * MAX_PODS),
    Ladder("incident", INCIDENT_BUCKET_SIZES,
           "config/settings.py:incident_bucket_sizes",
           escalation="rebuild"),
)


# -- checks ------------------------------------------------------------------

def check_ladder(lad: Ladder, where: str) -> list[Finding]:
    """The static contracts for ONE ladder (pure, stdlib-only)."""
    out: list[Finding] = []

    def hit(rule: str, msg: str) -> None:
        out.append(Finding(rule=rule, where=where,
                           message=f"ladder '{lad.name}': {msg}",
                           pass_name="lattice"))

    rungs = tuple(int(r) for r in lad.rungs)
    if not rungs:
        hit("ladder-gap", "declared with no rungs")
        return out
    if rungs[0] <= 0:
        hit("ladder-gap", f"rung {rungs[0]} is not positive")
    for lo, hi in zip(rungs[:-1], rungs[1:]):
        if hi <= lo:
            hit("ladder-gap",
                f"rungs not strictly increasing at {lo} -> {hi} "
                "(bucket_for would never select the shadowed rung)")
        elif lo > 0 and hi / lo > lad.max_gap_ratio:
            hit("ladder-gap",
                f"rung gap {lo} -> {hi} exceeds the {lad.max_gap_ratio:g}x "
                "padding-inflation bound — a count just past the lower "
                "rung pads to more than "
                f"{lad.max_gap_ratio:g}x its live size")
    if lad.covers:
        top = rungs[-1]
        if lad.escalation == "step":
            if lad.step <= 0:
                hit("ladder-gap",
                    "declares step escalation with no rounding step — "
                    "counts beyond the top rung have no planned capacity")
        elif lad.escalation == "none" and top < lad.covers:
            hit("ladder-gap",
                f"top rung {top} does not cover the declared scale "
                f"target {lad.covers} and no above-ladder escalation is "
                "declared — overflow mints an unplanned off-ladder "
                "compile mid-serve")
    elif lad.escalation == "step" and lad.step <= 0:
        hit("ladder-gap", "step escalation with no rounding step")
    if lad.divisor:
        for r in rungs:
            if lad.divisor_min:
                ok = (r % lad.divisor == 0 if r >= lad.divisor
                      else lad.divisor % r == 0)
            else:
                ok = r % lad.divisor == 0
            if not ok:
                hit("ladder-divisibility",
                    f"rung {r} does not align with the declared quantum "
                    f"{lad.divisor} (tiles/blocks would straddle a "
                    "capacity boundary)")
        if lad.step and lad.step % lad.divisor != 0:
            hit("ladder-divisibility",
                f"above-ladder step {lad.step} is not a multiple of the "
                f"quantum {lad.divisor} — beyond-top capacities would "
                "lose tile alignment exactly when slices are largest")
    return out


def _fixture_ladders(path: Path, rel: str) -> list[tuple[Ladder, str]]:
    """Module-level ``GRAFT_LADDERS = {...}`` literals (fixture trees)."""
    try:
        tree = ast.parse(path.read_text())
    except SyntaxError:
        return []
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "GRAFT_LADDERS"):
            try:
                decl = ast.literal_eval(node.value)
            except (ValueError, SyntaxError):
                return []
            if not isinstance(decl, dict):
                return []
            out = []
            for name, spec in sorted(decl.items()):
                out.append((Ladder(
                    name=str(name),
                    rungs=tuple(spec.get("rungs", ())),
                    defined_in=f"{rel}:{node.lineno}",
                    max_gap_ratio=float(spec.get("max_gap_ratio", 4.0)),
                    covers=int(spec.get("covers", 0)),
                    escalation=str(spec.get("escalation", "none")),
                    step=int(spec.get("step", 0)),
                    divisor=int(spec.get("divisor", 0)),
                    divisor_min=bool(spec.get("divisor_min", False)),
                ), f"{rel}:{node.lineno}"))
            return out
    return []


def run_ladders(root: "Path | str | None" = None) -> Report:
    """Check the declared registry (default) or every ``GRAFT_LADDERS``
    literal under a fixture ``root``."""
    report = Report()
    if root is None:
        for lad in LADDERS:
            report.findings.extend(check_ladder(lad, lad.defined_in))
        return report
    base = Path(root)
    for path in sorted(base.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel = path.relative_to(base).as_posix()
        for lad, where in _fixture_ladders(path, rel):
            report.findings.extend(check_ladder(lad, where))
    return report
