"""Pass 5 (graft-lattice), warm half: the warm-coverage proof.

:mod:`.dispatch_lattice` enumerates which tick variants serving can
dispatch; this module proves each of them is PRE-COMPILED by a declared
warm path — i.e. that the zero-post-warm-compile SLO is covered by
construction, not by the luck of which settings the chaos suites happen
to exercise. Coverage is declared in :data:`WARM_DECLARATIONS` as
``entrypoint -> (module, warm_fn, dispatch seam)`` and each declaration
is then VERIFIED against the source tree by AST:

* the module exists and defines ``warm_fn``;
* the dispatch seam — the function the serve path itself goes through
  (``_call_gnn_tick`` for the single-device GNN tiers, ``_tick_fn`` for
  the rules tick, the ``sharded_*_tick`` builders for the mesh tiers) —
  is reachable from ``warm_fn`` through the module-local call graph.
  Warming THROUGH the serve seam is the load-bearing property: it means
  the warm call compiles exactly the executable serving will request,
  whatever tier the live settings select, so the declaration cannot rot
  into warming a lookalike.

``warm-gap`` fires when a serve-reachable lattice entry has no
declaration, when a declared warm fn or module is missing, or when the
seam is not reachable from the warm fn (the warm path stopped going
through the dispatcher — it now warms something else). The companion
``lattice-unreachable`` (dead declared tiers) comes from
:func:`dispatch_lattice.check_unreachable` and is folded into the same
report.

Fixture trees participate via a module-level ``GRAFT_LATTICE = {...}``
literal (mirroring ``GRAFT_SENTINEL`` / ``GRAFT_LADDERS``)::

    GRAFT_LATTICE = {
        "reachable": ["tick.a", "tick.b"],   # serve-reachable entries
        "declared": ["tick.a", "tick.b"],    # registry declarations
        "warm": {"tick.a": "warm_a"},        # entry -> warm fn in module
    }

``warm-gap``: a reachable entry missing from ``warm`` or whose warm fn
is not defined in the module. ``lattice-unreachable``: a declared entry
absent from ``reachable``. Stdlib-only.
"""
from __future__ import annotations

import ast
from pathlib import Path

from .ast_lint import _call_name, package_root
from .dispatch_lattice import (OFF_SERVE_VARIANTS, RUNG_AXIS_VARIANTS,
                               check_unreachable, reachable_entries)
from .findings import Finding, Report
from .sentinel import _comment_waivers

# entrypoint -> (module rel path, warm fn, serve-dispatch seam).
# seam=None means existence-only: the warm fn IS the coverage (e.g. the
# surge growth pre-buckets, which return the shapes the generic warm
# loop then drives through the normal seam).
WARM_DECLARATIONS: dict[str, tuple] = {
    "streaming.rules_tick":
        ("rca/streaming.py", "warm", "_tick_fn"),
    "streaming.rules_tick.coalesced":
        ("rca/streaming.py", "warm", "_tick_fn"),
    "streaming.rules_tick.sharded":
        ("rca/streaming.py", "warm_mesh", "sharded_rules_tick"),
    "streaming.rules_tick.multitenant":
        ("rca/surge.py", "_growth_warm_buckets", None),
    # graft-swell: the elastic controller pre-compiles the target-shard
    # tick through the scorer's warm_mesh seam BEFORE scale_mesh adopts
    # the mesh, so a scale event pays an upload, never a compile
    "streaming.rules_tick.elastic":
        ("rca/elastic.py", "prewarm", "warm_mesh"),
    # every single-device GNN tier warms through the SAME dispatch seam
    # serving uses, so whichever tier the live settings select is the
    # one warm_gnn compiles — one declaration per tier keeps the proof
    # explicit per lattice entry even though the seam is shared
    "streaming.gnn_tick.bucketed":
        ("rca/gnn_streaming.py", "warm_gnn", "_call_gnn_tick"),
    "streaming.gnn_tick.coalesced":
        ("rca/gnn_streaming.py", "warm_gnn", "_call_gnn_tick"),
    "streaming.gnn_tick.fused":
        ("rca/gnn_streaming.py", "warm_gnn", "_call_gnn_tick"),
    "streaming.gnn_tick.fused.bf16":
        ("rca/gnn_streaming.py", "warm_gnn", "_call_gnn_tick"),
    "streaming.gnn_tick.dma":
        ("rca/gnn_streaming.py", "warm_gnn", "_call_gnn_tick"),
    "streaming.gnn_tick.dma.bf16":
        ("rca/gnn_streaming.py", "warm_gnn", "_call_gnn_tick"),
    "streaming.gnn_tick.dma.int8":
        ("rca/gnn_streaming.py", "warm_gnn", "_call_gnn_tick"),
    "streaming.gnn_tick.sharded":
        ("rca/gnn_streaming.py", "_warm_gnn_sharded", "sharded_gnn_tick"),
    "ingest.delta_pack":
        ("rca/streaming.py", "warm", "_delta_pack"),
}


class _ModuleGraph:
    """Module-local call graph: FunctionDef name -> bare call names in
    its body (``self.x()`` and ``x()`` both resolve to ``x``)."""

    def __init__(self, source: str):
        tree = ast.parse(source)
        self.defs: dict[str, set] = {}
        for n in ast.walk(tree):
            if not isinstance(n, ast.FunctionDef):
                continue
            calls = {_call_name(c).rsplit(".", 1)[-1]
                     for c in ast.walk(n) if isinstance(c, ast.Call)}
            # a later duplicate def (e.g. an overload in a subclass)
            # unions rather than shadows: coverage needs ANY path
            self.defs.setdefault(n.name, set()).update(calls)

    def reaches(self, start: str, seam: str) -> bool:
        """Is a call to ``seam`` reachable from ``start`` through
        functions defined in this module?"""
        if start not in self.defs:
            return False
        seen, frontier = set(), [start]
        while frontier:
            fn = frontier.pop()
            if fn in seen:
                continue
            seen.add(fn)
            calls = self.defs.get(fn, set())
            if seam in calls:
                return True
            frontier.extend(c for c in calls if c in self.defs)
        return False


def _check_real_tree(base: Path) -> list[Finding]:
    out: list[Finding] = []
    graphs: dict[str, _ModuleGraph] = {}

    def graph_for(rel: str) -> "_ModuleGraph | None":
        if rel not in graphs:
            path = base / rel
            graphs[rel] = (_ModuleGraph(path.read_text())
                           if path.is_file() else None)
        return graphs[rel]

    covered = set(WARM_DECLARATIONS) | set(OFF_SERVE_VARIANTS)
    for entry in sorted(reachable_entries()):
        if entry not in covered:
            out.append(Finding(
                rule="warm-gap", where=f"lattice:{entry}",
                message=f"serve-reachable lattice entry '{entry}' has no "
                        "warm declaration (analysis.warm_check."
                        "WARM_DECLARATIONS) — its first dispatch would "
                        "compile inside the serving window; add a warm "
                        "path through the dispatch seam and declare it",
                pass_name="lattice"))
    for entry, (rel, warm_fn, seam) in sorted(WARM_DECLARATIONS.items()):
        mod = graph_for(rel)
        where = f"{rel}:{warm_fn}"
        if mod is None:
            out.append(Finding(
                rule="warm-gap", where=where,
                message=f"warm declaration for '{entry}' names module "
                        f"'{rel}', which does not exist",
                pass_name="lattice"))
            continue
        if warm_fn not in mod.defs:
            out.append(Finding(
                rule="warm-gap", where=where,
                message=f"warm declaration for '{entry}' names "
                        f"'{warm_fn}', not defined in {rel} — the warm "
                        "path was renamed or removed without updating "
                        "the coverage proof", pass_name="lattice"))
            continue
        if seam is not None and not mod.reaches(warm_fn, seam):
            out.append(Finding(
                rule="warm-gap", where=where,
                message=f"'{warm_fn}' no longer reaches the dispatch "
                        f"seam '{seam}' — it warms a lookalike, not the "
                        f"executable serving dispatches for '{entry}'",
                pass_name="lattice"))
    return out


def _fixture_literal(tree: ast.Module) -> "tuple[dict, int] | None":
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "GRAFT_LATTICE"):
            try:
                return ast.literal_eval(node.value), node.lineno
            except ValueError:
                return None
    return None


def _check_fixture_tree(base: Path) -> list[Finding]:
    out: list[Finding] = []
    for path in sorted(base.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        source = path.read_text()
        if "GRAFT_LATTICE" not in source:
            continue
        try:
            tree = ast.parse(source)
        except SyntaxError:
            continue
        lit = _fixture_literal(tree)
        if lit is None:
            continue
        decl, lineno = lit
        rel = path.relative_to(base).as_posix()
        waivers = _comment_waivers(source)
        defined = {n.name for n in ast.walk(tree)
                   if isinstance(n, ast.FunctionDef)}
        reachable = list(decl.get("reachable", ()))
        declared = list(decl.get("declared", ()))
        warm = dict(decl.get("warm", {}))

        def hit(rule: str, message: str) -> None:
            waived, reason = False, ""
            for ln in (lineno, lineno - 1):
                w = waivers.get(ln)
                if w and (rule in w[0] or "all" in w[0]):
                    waived, reason = True, w[1]
                    break
            out.append(Finding(
                rule=rule, where=f"{rel}:{lineno}", message=message,
                pass_name="lattice", waived=waived, waiver_reason=reason))

        for entry in reachable:
            if entry not in warm:
                hit("warm-gap",
                    f"reachable entry '{entry}' has no warm declaration")
            elif warm[entry] not in defined:
                hit("warm-gap",
                    f"warm declaration for '{entry}' names "
                    f"'{warm[entry]}', not defined in this module")
        for entry in declared:
            if entry not in reachable:
                hit("lattice-unreachable",
                    f"declared entry '{entry}' is not reachable")
    return out


def run_warm_check(root: "Path | str | None" = None) -> Report:
    """Real tree (root=None): verify WARM_DECLARATIONS against the
    installed package and fold in dead-tier detection. Fixture tree:
    evaluate ``GRAFT_LATTICE`` literals."""
    report = Report()
    if root is None:
        report.findings.extend(_check_real_tree(package_root()))
        report.findings.extend(check_unreachable())
    else:
        report.findings.extend(_check_fixture_tree(Path(root)))
    return report
