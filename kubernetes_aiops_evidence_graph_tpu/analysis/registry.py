"""Hot-path entrypoint registry for the jaxpr audit.

Every entrypoint the perf work of PR 1 touched is registered here with
CANONICAL BENCH SHAPES (scaled-down but scale-separated: a [N, R, H]
materialization is ~5-10x the largest legitimate intermediate, so the
byte budget cleanly splits them) and the invariant spec it must satisfy.
Tracing is abstract (jax.make_jaxpr) — no FLOPs run, so registering big
shapes is free.

Adding a new jitted hot-path kernel? Register it here AND declare its
static/donate signature in ast_lint.JIT_DECLARATIONS — the self-audit
test (tests/test_graft_audit.py) and CI fail otherwise.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable

from .comms import COST_DEFAULT, CostSpec
from .invariants import CALLBACK_PRIMS, InvariantSpec
from .ladders import DMA_NODE_BLOCK as _DMA_NODE_BLOCK

MIB = 1 << 20

# canonical bench shapes (module-level so tests can assert against them)
N_NODES = 16384        # padded node rows
HIDDEN = 64
LAYERS = 3
N_INC = 128            # padded incident rows
# per-relation live edge counts for the 9 RelationKinds — drawn so the
# ladder caps are exact powers of two (rel_slice_offsets identity)
REL_COUNTS = (4096, 4096, 2048, 2048, 1024, 1024, 512, 512, 256)
# graph-axis shard count the sharded entrypoints trace with
GRAPH_SHARDS = 2

# the hot-path budget: comfortably above the largest legitimate
# intermediate at the canonical shapes ([N, H] f32 = 4 MiB) and far below
# a [N, R, H] materialization (36 MiB) or a full [E, H] message table
HOT_BUDGET = 8 * MIB
# the reference (parity-oracle) path's budget pins its KNOWN peak — the
# [N, R, H] einsum (36 MiB at canonical shapes); anything beyond that is
# new regression even for the oracle
REFERENCE_BUDGET = 40 * MIB

# canonical shapes for the Pallas gather_matmul_segment entrypoints:
# DELIBERATELY small-N / big-slice so the byte budget separates what the
# kernel may materialize from what it must not — the unavoidable [N, H]
# accumulator/output is 1 MiB, every in-kernel intermediate is
# [EDGE_TILE, H] tile scale (128 KiB), while a single full-slice
# [E_r, H] gather/message materialization (the XLA kernel's working set)
# is >= 4 MiB and a whole-[E, H] table ~15 MiB. The 2 MiB budget admits
# the accumulator and rejects anything slice-scaled.
PALLAS_N = 4096
PALLAS_REL_COUNTS = tuple(4 * c for c in REL_COUNTS)
PALLAS_TILE_BUDGET = 2 * MIB

# graft-fuse budgets. The fused streaming tick keeps every [N, H]
# activation VMEM-resident (pn=4096, H=64 → 1 MiB per live table): its
# largest legitimate in-kernel intermediate is one whole-table value
# (the embed/layer-update products), so 4 MiB comfortably admits the
# resident math and rejects anything [E, H]- or [N, R, H]-scaled. The
# gms vjp trace carries forward + both backward kernels at the Pallas
# canonical shapes — its peak is the co-live (h, cotangent, dh)
# tables + the [R, H, K] grad accumulator, still well under the
# slice-materialization scale the budget exists to reject.
FUSED_TICK_BUDGET = 4 * MIB
PALLAS_VJP_BUDGET = 6 * MIB

# graft-tide: the beyond-VMEM DMA tick streams node blocks and edge
# tiles through a double-buffered VMEM window — at its canonical shapes
# (pn = N_NODES = 16384, node_block = 2048) the largest legitimate
# in-kernel value is one [node_block, H] f32 window product (512 KiB)
# plus tile-scale edge math, so 8 MiB comfortably admits the windowed
# math and rejects any [N, H]-resident (4 MiB × co-live tables) or
# [E, H] materialization that would mean the kernel stopped streaming.
DMA_NODE_BLOCK = _DMA_NODE_BLOCK   # declared in analysis/ladders.py
DMA_TICK_BUDGET = 8 * MIB

# bucketed forward paths may not contain a set-scatter at all — the only
# scatters are the per-slice 1-D dst segment-adds
NO_SET_SCATTER = CALLBACK_PRIMS | frozenset({"scatter"})


class SkipEntrypoint(Exception):
    """Raised by a builder when its environment can't trace it (e.g. a
    sharded entry on a single-device host) — recorded, not a violation."""


@dataclass(frozen=True)
class Entrypoint:
    name: str
    # () -> (callable, args tuple); statics must already be bound
    build: Callable[[], tuple[Callable, tuple]]
    spec: InvariantSpec
    notes: str = ""
    # collective-traffic contract for the graft-cost pass; None means the
    # single-device default (no collectives at all) — see comms.COST_DEFAULT
    cost: "CostSpec | None" = None


def _np():
    import numpy as np
    return np


def _rel_offsets():
    from ..graph.snapshot import rel_slice_offsets
    return rel_slice_offsets(REL_COUNTS)


def _gnn_arrays(n: int = N_NODES, b: int = N_INC):
    """Canonical relation-bucketed snapshot arrays (concrete, cheap)."""
    np = _np()
    from ..graph.schema import DIM
    offs = _rel_offsets()
    pe = int(offs[-1])
    rng = np.random.default_rng(0)
    edge_src = rng.integers(0, n, pe).astype(np.int32)
    edge_dst = np.zeros(pe, np.int32)
    edge_rel = np.full(pe, -1, np.int32)
    edge_mask = np.zeros(pe, np.float32)
    for r, (lo, hi) in enumerate(zip(offs[:-1], offs[1:])):
        c = REL_COUNTS[r]
        # live prefix dst-sorted per the snapshot layout contract
        edge_dst[lo:lo + c] = np.sort(rng.integers(0, n, c)).astype(np.int32)
        edge_dst[lo + c:hi] = n - 1          # padding pinned to last row
        edge_rel[lo:lo + c] = r
        edge_mask[lo:lo + c] = 1.0
    return {
        "features": np.zeros((n, DIM), np.float32),
        "node_kind": np.zeros(n, np.int32),
        "node_mask": np.ones(n, np.float32),
        "edge_src": edge_src,
        "edge_dst": edge_dst,
        "edge_rel": edge_rel,
        "edge_mask": edge_mask,
        "incident_nodes": np.zeros(b, np.int32),
        "incident_mask": np.ones(b, np.float32),
        "rel_offsets": offs,
    }


def _params():
    import jax
    from ..rca import gnn
    return gnn.init_params(jax.random.PRNGKey(0), hidden=HIDDEN,
                           layers=LAYERS)


def _forward_entry(compute_dtype=None, bucketed: bool = True,
                   slices_sorted: bool = True):
    def build():
        from ..rca import gnn
        a = _gnn_arrays()
        params = _params()
        if bucketed:
            fn = partial(gnn.forward, rel_offsets=a["rel_offsets"],
                         slices_sorted=slices_sorted,
                         compute_dtype=compute_dtype)
        else:
            fn = partial(gnn.forward, sorted_by_dst=True)
        args = (params, a["features"], a["node_kind"], a["node_mask"],
                a["edge_src"], a["edge_dst"], a["edge_rel"], a["edge_mask"],
                a["incident_nodes"])
        return fn, args
    return build


def _train_step_build():
    try:
        import optax
    except ImportError as exc:                  # pragma: no cover
        raise SkipEntrypoint(f"optax unavailable: {exc}")
    from ..rca import gnn
    a = _gnn_arrays()
    np = _np()
    params = _params()
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)
    step = gnn.make_train_step(tx)
    batch = {k: a[k] for k in (
        "features", "node_kind", "node_mask", "edge_src", "edge_dst",
        "edge_rel", "edge_mask", "incident_nodes")}
    batch["labels"] = np.zeros(N_INC, np.int32)
    batch["label_mask"] = a["incident_mask"]
    fn = partial(step, rel_offsets=a["rel_offsets"], slices_sorted=True)
    return fn, (params, opt_state, batch)


def _finetune_step_build():
    """graft-evolve: the online fine-tune step (learn/trainer.py) at the
    canonical training shapes — the offline train step's loss through the
    bucketed kernel PLUS the proximal anchor term pulling the candidate
    toward the serving checkpoint. The anchor adds elementwise work only
    (sum of squared diffs over ~params-sized leaves), so the jaxpr must
    stay inside the same budget/sorted-scatter contract as
    gnn.train_step.bucketed, and the ratchet pins that the anchor never
    quietly grows into something matmul-shaped."""
    try:
        import optax
    except ImportError as exc:                  # pragma: no cover
        raise SkipEntrypoint(f"optax unavailable: {exc}")
    import numpy as np
    from ..learn.trainer import make_finetune_step
    a = _gnn_arrays()
    params = _params()
    anchor = _params()
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)
    step = make_finetune_step(tx)
    batch = {k: a[k] for k in (
        "features", "node_kind", "node_mask", "edge_src", "edge_dst",
        "edge_rel", "edge_mask", "incident_nodes")}
    batch["labels"] = np.zeros(N_INC, np.int32)
    batch["label_mask"] = a["incident_mask"]
    fn = partial(step, rel_offsets=a["rel_offsets"], slices_sorted=True)
    return fn, (params, opt_state, anchor, np.float32(1e-3), batch)


def _sharded_build(halo: str):
    def build():
        import jax
        if len(jax.devices()) < 2:
            raise SkipEntrypoint("needs >= 2 devices for the graph axis")
        np = _np()
        from ..parallel.mesh import make_mesh
        from ..parallel.sharded_gnn import _sharded_loss
        d = len(jax.devices())
        graph = GRAPH_SHARDS
        dp = d // graph
        mesh = make_mesh(dp=dp, graph=graph)
        a = _gnn_arrays()
        n, b = N_NODES, N_INC
        nps = n // graph
        offs = a["rel_offsets"]
        pe_shard = int(offs[-1])
        # PartitionedGraph shapes (parallel/partition.py): node/edge
        # arrays carry a leading [G] shard axis, incidents a leading [dp]
        # axis; the per-shard slice tables are SHARED, so every shard sees
        # one full offsets-worth of edge rows with LOCAL dst
        def g_stack(x):
            return np.stack([x] * graph)
        loss = _sharded_loss(mesh, halo=halo, rel_offsets=offs,
                             slices_sorted=(halo == "allgather"))
        args = (
            _params(),
            a["features"].reshape(graph, nps, -1),
            a["node_kind"].reshape(graph, nps),
            a["node_mask"].reshape(graph, nps),
            g_stack(a["edge_src"]),
            g_stack(np.clip(a["edge_dst"], 0, nps - 1)),
            g_stack(a["edge_rel"]), g_stack(a["edge_mask"]),
            a["incident_nodes"].reshape(dp, b // dp),
            a["incident_mask"].reshape(dp, b // dp),
            np.zeros((dp, b // dp), np.int32),
        )
        assert args[4].shape == (graph, pe_shard)
        return loss, args
    return build


def _rules_tick_build(pk: int = 64, rk: int = 4):
    np = _np()
    from ..graph.schema import DIM
    from ..rca.streaming import _tick
    pn, pi, width, pair_width = 4096, 32, 128, 16
    ints = np.zeros(pk + 2 * rk + 2 * rk * width, np.int32)
    fn = partial(_tick, padded_incidents=pi, pair_width=pair_width,
                 pk=pk, rk=rk, width=width)
    args = (np.zeros((pn, DIM), np.float32), ints,
            np.zeros((pk, DIM), np.float32),
            np.zeros((pi, width), np.int32), np.zeros(pi, np.int32),
            np.full((pi, width), pair_width, np.int32),
            np.zeros(pi, np.float32))
    return fn, args


def _rules_tick_coalesced_build():
    """The queue-full coalescing bound: a merged delta at the TOP of the
    delta/row ladders (graft-pipeline). tick_async() never mints a shape
    beyond these — a larger merge stalls for a pipeline slot instead —
    so this entrypoint pins the worst tick the executor may dispatch."""
    from ..rca.streaming import _DELTA_BUCKETS, _ROW_BUCKETS
    return _rules_tick_build(pk=_DELTA_BUCKETS[-1], rk=_ROW_BUCKETS[-1])


# graft-surge canonical pack: TENANTS regions of the canonical streaming
# shapes packed onto one resident state — the multi-tenant tick is the
# SAME jitted _tick at the summed region shapes (pn·T node rows, pi·T
# incident rows scored in ONE pass), so its cost must scale exactly
# linearly in T with zero new collectives
SURGE_TENANTS = 4


def _rules_tick_multitenant_build():
    """graft-surge: the packed cross-tenant rules tick — SURGE_TENANTS
    tenant regions (4096 node rows / 32 incident rows each, the
    streaming canonical shapes) in one resident state; every tenant's
    live incidents score in one device pass. Reuses streaming._tick
    (donation contract and all); this entry pins the packed shapes in
    the ratchet so tenant-packing can never quietly change the
    per-incident cost envelope."""
    np = _np()
    from ..graph.schema import DIM
    from ..rca.streaming import _tick
    t = SURGE_TENANTS
    pn, pi, width, pair_width = 4096 * t, 32 * t, 128, 16
    pk, rk = 64, 4
    ints = np.zeros(pk + 2 * rk + 2 * rk * width, np.int32)
    fn = partial(_tick, padded_incidents=pi, pair_width=pair_width,
                 pk=pk, rk=rk, width=width)
    args = (np.zeros((pn, DIM), np.float32), ints,
            np.zeros((pk, DIM), np.float32),
            np.zeros((pi, width), np.int32), np.zeros(pi, np.int32),
            np.full((pi, width), pair_width, np.int32),
            np.zeros(pi, np.float32))
    return fn, args


def _delta_pack_build():
    """graft-intake: the columnar staged-slab split — ONE int32 host→
    device buffer per tick sliced into the fused tick's (ints, f_rows)
    operands, the feature segment bitcast back to f32 (bit-exact). Zero
    FLOPs, bytes ≈ 2× the slab; traced at the canonical streaming delta
    shapes (pk=64, rk=4, width=128)."""
    np = _np()
    from ..graph.schema import DIM
    from ..rca.streaming import _delta_pack
    pk, rk, width = 64, 4, 128
    li = pk + 2 * rk + 2 * rk * width
    fn = partial(_delta_pack, li=li, pk=pk, dim=DIM)
    return fn, (np.zeros(li + pk * DIM, np.int32),)


def _gnn_tick_build(pk: int = 64, ek: int = 256):
    np = _np()
    from ..graph.schema import DIM
    from ..rca.gnn_streaming import _gnn_tick
    offs = _rel_offsets()
    pn, pi = 4096, 32
    pe = int(offs[-1])
    ints = np.zeros(3 * pk + 5 * ek + 2 * pi, np.int32)
    # the mirror never promises slices_sorted (slot reuse under churn)
    fn = partial(_gnn_tick, pk=pk, ek=ek, pi=pi, rel_offsets=offs,
                 slices_sorted=False, compute_dtype=None)
    args = (_params(), np.zeros((pn, DIM), np.float32),
            np.zeros(pn, np.int32), np.ones(pn, np.float32),
            np.zeros(pe, np.int32), np.zeros(pe, np.int32),
            np.full(pe, -1, np.int32), np.zeros(pe, np.float32), ints)
    return fn, args


def _gnn_tick_coalesced_build():
    """Worst coalesced GNN tick the pipelined executor may dispatch:
    aux + edge deltas merged to the top of the _DELTA_BUCKETS ladder
    (each pending edge packs two directed slot entries, so edge-heavy
    queue-full merges land here first)."""
    from ..rca.streaming import _DELTA_BUCKETS
    return _gnn_tick_build(pk=_DELTA_BUCKETS[-1], ek=_DELTA_BUCKETS[-1])


def _gnn_fused_tick_build(compute_dtype: str | None = None):
    """graft-fuse: the fused streaming tick — ONE pallas_call from the
    packed delta scatter through the relation-bucketed message pass to
    the logits/probs reduction, at the canonical GNN-tick shapes. The
    [N, H] activations live in VMEM scratch for the whole tick, so the
    modeled HBM bytes/tick must land STRICTLY below the composed
    streaming.gnn_tick.bucketed path's — the ratchet pins the lower
    floor once recorded. ``compute_dtype="bfloat16"`` traces the
    graft-tide bf16-operand variant (f32 accumulation pinned by
    ``bf16_accum_f32``)."""
    np = _np()
    from ..graph.schema import DIM
    from ..rca.gnn_streaming import _gnn_fused_tick
    offs = _rel_offsets()
    pn, pi = 4096, 32
    pe = int(offs[-1])
    pk = ek = 64
    ints = np.zeros(3 * pk + 5 * ek + 2 * pi, np.int32)
    fn = partial(_gnn_fused_tick, pk=pk, ek=ek, pi=pi, rel_offsets=offs,
                 compute_dtype=compute_dtype)
    args = (_params(), np.zeros((pn, DIM), np.float32),
            np.zeros(pn, np.int32), np.ones(pn, np.float32),
            np.zeros(pe, np.int32), np.zeros(pe, np.int32),
            np.full(pe, -1, np.int32), np.zeros(pe, np.float32), ints)
    return fn, args


def _gnn_dma_tick_build(feat_quant: str = ""):
    """graft-tide: the beyond-VMEM streaming tick — edge mirror, node
    features, and the persistent [N, H] hidden state stay HBM-resident
    (ANY memory space); the kernel streams EDGE_TILE/node-block windows
    through double-buffered VMEM via explicit async copies. Traced at
    pn = N_NODES (16384, 4× the resident canonical — a shape whose
    resident working set the fused tick's own VMEM guard rejects) so
    the cost model prices the DMA tile traffic, not a resident stream.
    ``feat_quant`` picks the quantized node-feature table tier
    ("bfloat16" | "int8" — int8 carries its per-column scale and the
    delta rows arrive pre-quantized against the frozen scale)."""
    np = _np()
    from ..graph.schema import DIM
    from ..rca.gnn_streaming import _gnn_dma_tick, _gnn_dma_tick_q
    offs = _rel_offsets()
    pn, pi = N_NODES, 32
    pe = int(offs[-1])
    pk = ek = 64
    ints = np.zeros(3 * pk + 5 * ek + 2 * pi, np.int32)
    h = np.zeros((pn, HIDDEN), np.float32)
    mirror = (np.zeros(pn, np.int32), np.ones(pn, np.float32),
              np.zeros(pe, np.int32), np.zeros(pe, np.int32),
              np.full(pe, -1, np.int32), np.zeros(pe, np.float32), ints)
    if not feat_quant:
        fn = partial(_gnn_dma_tick, pk=pk, ek=ek, pi=pi, rel_offsets=offs,
                     node_block=DMA_NODE_BLOCK, compute_dtype=None)
        return fn, (_params(), np.zeros((pn, DIM), np.float32), *mirror,
                    h, h.copy())
    import jax.numpy as jnp
    qdt = jnp.int8 if feat_quant == "int8" else jnp.bfloat16
    scale = (np.ones(DIM, np.float32) if feat_quant == "int8" else None)
    fn = partial(_gnn_dma_tick_q, pk=pk, ek=ek, pi=pi, rel_offsets=offs,
                 node_block=DMA_NODE_BLOCK, compute_dtype=None,
                 feat_quant=feat_quant)
    return fn, (_params(), jnp.zeros((pn, DIM), qdt), *mirror,
                h, h.copy(), jnp.zeros((pk, DIM), qdt), scale)


def _pallas_gms_vjp_build():
    """graft-fuse: gradients THROUGH the Pallas gather_matmul_segment —
    the custom_vjp's forward kernel plus both backward kernels (the
    transposed-layout dh pass and the per-relation [H, K] grad-matmul
    accumulator) traced as one value_and_grad at the Pallas canonical
    shapes. Pins that the backward stays tile-shaped: no [E_r, H]
    slice materialization, no collectives, f32 accumulation."""
    import jax
    np = _np()
    from ..graph.snapshot import rel_slice_offsets
    from ..ops.pallas_segment import pallas_gather_matmul_segment
    offs = rel_slice_offsets(PALLAS_REL_COUNTS)
    n, h = PALLAS_N, HIDDEN
    pe = int(offs[-1])
    rng = np.random.default_rng(0)
    src = rng.integers(0, n, pe).astype(np.int32)
    dst = np.full(pe, n - 1, np.int32)
    mask = np.zeros(pe, np.float32)
    for r, (lo, hi) in enumerate(zip(offs[:-1], offs[1:])):
        c = PALLAS_REL_COUNTS[r]
        dst[lo:lo + c] = np.sort(rng.integers(0, n, c)).astype(np.int32)
        mask[lo:lo + c] = 1.0
    srcj, dstj, maskj = src, dst, mask

    def loss(hh, ww):
        return pallas_gather_matmul_segment(
            hh, ww, srcj, dstj, maskj, offs, n, slices_sorted=True,
            interpret=True).sum()

    fn = jax.grad(loss, argnums=(0, 1))
    return fn, (np.zeros((n, h), np.float32),
                np.zeros((len(PALLAS_REL_COUNTS), h, h), np.float32))


def _sharded_rules_tick_build():
    """graft-fleet: the mesh-resident rules tick at the canonical
    streaming shapes on a (1 x GRAPH_SHARDS) serving mesh — per-shard
    routed feature deltas, owner-local evidence fold, ONE verdict psum."""
    from ..parallel.mesh import serving_mesh
    mesh = serving_mesh(GRAPH_SHARDS)
    if mesh is None:
        raise SkipEntrypoint(
            f"needs >= {GRAPH_SHARDS} devices for the graph axis")
    np = _np()
    from ..graph.schema import DIM
    from ..parallel.sharded_streaming import sharded_rules_tick
    g = GRAPH_SHARDS
    pn, pi, width, pair_width = 4096, 32, 128, 16
    pk, rk = 64, 4
    fn = sharded_rules_tick(mesh, pn // g, pi, pair_width, pk, rk, width)
    ints = np.zeros((g, pk + 2 * rk + 2 * rk * width), np.int32)
    args = (np.zeros((pn, DIM), np.float32), ints,
            np.zeros((g, pk, DIM), np.float32),
            np.zeros((pi, width), np.int32), np.zeros(pi, np.int32),
            np.full((pi, width), pair_width, np.int32),
            np.zeros(pi, np.float32))
    return fn, args


# graft-swell: the shard count an elastic scale-UP re-lands the sharded
# tick at — one rung up the divisor ladder from GRAPH_SHARDS, so the
# audit proves the scale target's jaxpr obeys the same collective
# contract as the base sharded tier (per-shard shapes shrink, the one
# verdict psum stays byte-identical)
ELASTIC_SHARDS = 4


def _elastic_rules_tick_build():
    """graft-swell: the SAME sharded rules tick executable at the
    elastic scale target D'=ELASTIC_SHARDS — what ElasticController
    pre-warms before shield.scale_mesh adopts the wider mesh."""
    from ..parallel.mesh import serving_mesh
    mesh = serving_mesh(ELASTIC_SHARDS)
    if mesh is None:
        raise SkipEntrypoint(
            f"needs >= {ELASTIC_SHARDS} devices for the graph axis")
    np = _np()
    from ..graph.schema import DIM
    from ..parallel.sharded_streaming import sharded_rules_tick
    g = ELASTIC_SHARDS
    pn, pi, width, pair_width = 4096, 32, 128, 16
    pk, rk = 64, 4
    fn = sharded_rules_tick(mesh, pn // g, pi, pair_width, pk, rk, width)
    ints = np.zeros((g, pk + 2 * rk + 2 * rk * width), np.int32)
    args = (np.zeros((pn, DIM), np.float32), ints,
            np.zeros((g, pk, DIM), np.float32),
            np.zeros((pi, width), np.int32), np.zeros(pi, np.int32),
            np.full((pi, width), pair_width, np.int32),
            np.zeros(pi, np.float32))
    return fn, args


# per-shard relation-slice capacities the sharded GNN streaming tick
# traces with: the canonical REL_COUNTS split over the graph axis (edges
# partition by dst owner), floored so every relation keeps a live slice
STREAM_SHARD_REL_COUNTS = tuple(
    max(c // GRAPH_SHARDS, 64) for c in REL_COUNTS)


def _sharded_gnn_tick_build():
    """graft-fleet: the mesh-resident GNN streaming tick — per-shard edge
    regions, ring-halo message pass ((LAYERS+1)*GRAPH_SHARDS ppermutes of
    [N/D, H] blocks, zero all-gathers), ring readout."""
    from ..parallel.mesh import serving_mesh
    mesh = serving_mesh(GRAPH_SHARDS)
    if mesh is None:
        raise SkipEntrypoint(
            f"needs >= {GRAPH_SHARDS} devices for the graph axis")
    np = _np()
    from ..graph.schema import DIM
    from ..graph.snapshot import rel_slice_offsets
    from ..parallel.sharded_streaming import sharded_gnn_tick
    g = GRAPH_SHARDS
    pn, pi = 4096, 32
    offs = rel_slice_offsets(STREAM_SHARD_REL_COUNTS)
    pe_shard = int(offs[-1])
    pe = pe_shard * g
    pk = ek = 64
    # the sharded mirror never promises slices_sorted under churn
    fn = sharded_gnn_tick(mesh, pn // g, pe_shard, pi, pk, ek,
                          rel_offsets=offs, slices_sorted=False,
                          compute_dtype=None)
    ints = np.zeros((g, 3 * pk + 5 * ek + 2 * pi), np.int32)
    args = (_params(), np.zeros((pn, DIM), np.float32),
            np.zeros(pn, np.int32), np.ones(pn, np.float32),
            np.zeros(pe, np.int32), np.zeros(pe, np.int32),
            np.full(pe, -1, np.int32), np.zeros(pe, np.float32), ints)
    return fn, args


def _gms_build(compute_dtype=None):
    def build():
        np = _np()
        from ..ops.segment import gather_matmul_segment
        offs = _rel_offsets()
        n, h = 8192, HIDDEN
        pe = int(offs[-1])
        fn = partial(gather_matmul_segment, rel_offsets=offs,
                     num_segments=n, slices_sorted=True,
                     compute_dtype=compute_dtype)
        args = (np.zeros((n, h), np.float32),
                np.zeros((len(REL_COUNTS), h, h), np.float32),
                np.zeros(pe, np.int32), np.zeros(pe, np.int32),
                np.zeros(pe, np.float32))
        return fn, args
    return build


def _pallas_gms_build(compute_dtype=None):
    def build():
        np = _np()
        from ..graph.snapshot import rel_slice_offsets
        from ..ops.pallas_segment import pallas_gather_matmul_segment
        offs = rel_slice_offsets(PALLAS_REL_COUNTS)
        n, h = PALLAS_N, HIDDEN
        pe = int(offs[-1])
        rng = np.random.default_rng(0)
        src = rng.integers(0, n, pe).astype(np.int32)
        # live prefixes dst-sorted, padding pinned to the last row — the
        # snapshot layout contract, same as _gnn_arrays
        dst = np.full(pe, n - 1, np.int32)
        mask = np.zeros(pe, np.float32)
        for r, (lo, hi) in enumerate(zip(offs[:-1], offs[1:])):
            c = PALLAS_REL_COUNTS[r]
            dst[lo:lo + c] = np.sort(rng.integers(0, n, c)).astype(np.int32)
            mask[lo:lo + c] = 1.0
        fn = partial(pallas_gather_matmul_segment, rel_offsets=offs,
                     num_segments=n, slices_sorted=True,
                     compute_dtype=compute_dtype, interpret=True)
        args = (np.zeros((n, h), np.float32),
                np.zeros((len(PALLAS_REL_COUNTS), h, h), np.float32),
                src, dst, mask)
        return fn, args
    return build


def _forward_pallas_build():
    from ..rca import gnn
    a = _gnn_arrays()
    fn = partial(gnn.forward, rel_offsets=a["rel_offsets"],
                 slices_sorted=True, pallas=True)
    args = (_params(), a["features"], a["node_kind"], a["node_mask"],
            a["edge_src"], a["edge_dst"], a["edge_rel"], a["edge_mask"],
            a["incident_nodes"])
    return fn, args


def _k_hop_build():
    np = _np()
    from ..ops.propagate import k_hop_reach
    n, e, b = 4096, 16384, 32
    fn = partial(k_hop_reach, num_nodes=n, hops=3)
    args = (np.zeros(b, np.int32), np.ones(b, np.float32),
            np.zeros(e, np.int32), np.zeros(e, np.int32),
            np.ones(e, np.float32))
    return fn, args


def _propagate_build():
    np = _np()
    from ..ops.propagate import propagate_labels
    n, e = 65536, 262144
    fn = partial(propagate_labels, num_nodes=n, iterations=3)
    args = (np.zeros(n, np.float32), np.zeros(e, np.int32),
            np.zeros(e, np.int32), np.ones(e, np.float32))
    return fn, args


def _shield_shapes():
    """Canonical resident-state shapes for the graft-shield snapshot
    kernels: the rules scorer's resident set at the audit's canonical
    node/incident buckets (features + the three evidence tables)."""
    from ..graph.schema import DIM
    width, pair_width = 128, 16
    return (((N_NODES, DIM), "float32"),
            ((N_INC, width), "int32"),
            ((N_INC,), "int32"),
            ((N_INC, width), "int32")), pair_width


def _snapshot_pack_build():
    np = _np()
    from ..rca.shield import _snapshot_pack
    layout, pw = _shield_shapes()
    args = tuple(
        np.zeros(shp, np.float32) if dt == "float32"
        else np.full(shp, pw, np.int32)
        for shp, dt in layout)
    return _snapshot_pack, args


def _snapshot_unpack_build():
    np = _np()
    from ..rca.shield import _snapshot_unpack
    layout, _pw = _shield_shapes()
    total = 0
    for shp, _dt in layout:
        n = 1
        for d in shp:
            n *= d
        total += n
    fn = partial(_snapshot_unpack, layout=layout)
    return fn, (np.zeros(total, np.int32),)


def _attest_fold_build():
    """graft-heal: the per-shard attestation fold at the canonical
    resident-state shapes, D=1 (the single-device census — sharded it is
    the same shard-local fold with only the [shards] result crossing).
    Bitcast + modular uint32 sums only: zero dot FLOPs, zero collectives
    by contract — the attestation pass may never grow compute or go
    distributed implicitly."""
    np = _np()
    from ..graph.schema import DIM
    from ..rca.heal import attest_fold
    fn = partial(attest_fold, shards=1)
    args = (np.zeros((N_NODES, DIM), np.float32),
            np.zeros(N_NODES, np.int32),
            np.ones(N_NODES, np.float32))
    return fn, args


def _score_device_build():
    np = _np()
    from ..graph.schema import DIM
    from ..rca.tpu_backend import _score_device
    pn, pi, w, pw = N_NODES, N_INC, 128, 16
    fn = partial(_score_device, padded_incidents=pi, pair_width=pw)
    args = (np.zeros((pn, DIM), np.float32),
            np.zeros((pi, w), np.int32), np.zeros(pi, np.int32),
            np.full((pi, w), pw, np.int32), np.zeros(pi, np.float32))
    return fn, args


_HOT = InvariantSpec(forbid_primitives=NO_SET_SCATTER,
                     max_intermediate_bytes=HOT_BUDGET,
                     expect_sorted_scatter=True)
# resident-state ticks legitimately apply deltas via 1-D set-scatters, and
# their mirror never promises within-slice dst order under churn
_TICK = InvariantSpec(max_intermediate_bytes=HOT_BUDGET)

# -- collective-traffic contracts (graft-cost; comms.py) -------------------
# Entrypoints without a CostSpec get the single-device default: no
# collectives at all. The two sharded halos declare their EXACT census at
# canonical shapes — counts are loop-weighted (the ring's per-layer
# fori_loop lowers to a scan of length GRAPH_SHARDS).
_NPS = N_NODES // GRAPH_SHARDS
# allgather halo: one full-[N, H] gather per layer + one for the readout,
# plus the two scalar loss psums over dp; never a ring or a reduce-scatter
_ALLGATHER_COST = CostSpec(
    expect_counts={"all_gather": LAYERS + 1, "psum": 2, "ppermute": 0},
    forbid=("reduce_scatter", "psum_scatter", "all_to_all"),
    max_bytes_per_op={"all_gather": N_NODES * HIDDEN * 4},
    max_total_bytes=(LAYERS + 1) * N_NODES * HIDDEN * 4 + 1024,
)
# ring halo: GRAPH_SHARDS ppermutes of one [N/D, H] block per layer plus
# the streamed readout, and ZERO full-[N, H] all-gathers — the whole point
# of the ring is O(N/D) resident remote bytes
_RING_COST = CostSpec(
    expect_counts={"ppermute": (LAYERS + 1) * GRAPH_SHARDS, "psum": 2},
    forbid=("all_gather", "reduce_scatter", "psum_scatter", "all_to_all"),
    max_bytes_per_op={"ppermute": _NPS * HIDDEN * 4},
    max_total_bytes=(LAYERS + 1) * GRAPH_SHARDS * _NPS * HIDDEN * 4 + 1024,
)
# graft-fleet streaming ticks (canonical shapes: pn=4096, pi=32 rows,
# DIM=48 features, pair_width=16). Rules: the owner-fold needs ONE psum
# of the concatenated [rows, DIM+PW] counts — zero ppermutes, zero
# all-gathers (the fold moves per-row counts, never node blocks). GNN:
# exactly (LAYERS+1)*D ppermutes of one [N/D, H] embedding block each
# (LAYERS assembly rings + the readout ring) and nothing else — the
# same contract the snapshot ring kernels already obey.
_STREAM_NPS = 4096 // GRAPH_SHARDS
_SHARDED_RULES_TICK_COST = CostSpec(
    expect_counts={"psum": 1, "ppermute": 0, "all_gather": 0},
    forbid=("all_to_all", "reduce_scatter", "psum_scatter", "pshuffle"),
    max_bytes_per_op={"psum": 32 * (48 + 16) * 4},
    max_total_bytes=32 * (48 + 16) * 4 + 1024,
)
# graft-swell: the elastic target inherits the sharded tier's contract
# verbatim — the verdict psum is [pi, DIM+PW] regardless of D', so the
# byte caps do not scale with the shard count
_ELASTIC_RULES_TICK_COST = CostSpec(
    expect_counts={"psum": 1, "ppermute": 0, "all_gather": 0},
    forbid=("all_to_all", "reduce_scatter", "psum_scatter", "pshuffle"),
    max_bytes_per_op={"psum": 32 * (48 + 16) * 4},
    max_total_bytes=32 * (48 + 16) * 4 + 1024,
)
_SHARDED_GNN_TICK_COST = CostSpec(
    expect_counts={"ppermute": (LAYERS + 1) * GRAPH_SHARDS, "psum": 0,
                   "all_gather": 0},
    forbid=("all_to_all", "reduce_scatter", "psum_scatter", "pshuffle"),
    max_bytes_per_op={"ppermute": _STREAM_NPS * HIDDEN * 4},
    max_total_bytes=(LAYERS + 1) * GRAPH_SHARDS * _STREAM_NPS * HIDDEN * 4
    + 1024,
)


ENTRYPOINTS: tuple[Entrypoint, ...] = (
    Entrypoint(
        "gnn.forward.bucketed", _forward_entry(), _HOT,
        notes="relation-bucketed hot path, slices_sorted fast path"),
    Entrypoint(
        "gnn.forward.bucketed.bf16",
        _forward_entry(compute_dtype="bfloat16"),
        InvariantSpec(forbid_primitives=NO_SET_SCATTER,
                      max_intermediate_bytes=HOT_BUDGET,
                      expect_sorted_scatter=True, bf16_accum_f32=True),
        notes="bf16 matmul operands must accumulate into f32"),
    Entrypoint(
        "gnn.forward.reference", _forward_entry(bucketed=False),
        InvariantSpec(forbid_primitives=NO_SET_SCATTER,
                      max_intermediate_bytes=REFERENCE_BUDGET,
                      expect_sorted_scatter=True),
        notes="transform-then-gather parity oracle; budget pins its known "
              "[N, R, H] peak so even the oracle cannot regress further"),
    Entrypoint(
        "gnn.train_step.bucketed", _train_step_build,
        InvariantSpec(max_intermediate_bytes=HOT_BUDGET,
                      expect_sorted_scatter=True),
        notes="value_and_grad + adam through the bucketed kernel (gather "
              "transposes are 1-D scatter-adds)"),
    Entrypoint(
        "learn.finetune_step", _finetune_step_build,
        InvariantSpec(max_intermediate_bytes=HOT_BUDGET,
                      expect_sorted_scatter=True),
        notes="graft-evolve online fine-tune: offline-step loss + "
              "proximal anchor 0.5*w*||theta - serving||^2 (elementwise "
              "only); donates (params, opt_state), the anchor is read "
              "per step; explicit zero-collective CostSpec — the "
              "background trainer must never go distributed implicitly "
              "(the sharded tier is the separately-pinned "
              "sharded_gnn.loss.ring entrypoint)",
        cost=COST_DEFAULT),
    Entrypoint(
        "sharded_gnn.loss.allgather.bucketed", _sharded_build("allgather"),
        InvariantSpec(max_intermediate_bytes=HOT_BUDGET,
                      expect_sorted_scatter=True),
        cost=_ALLGATHER_COST),
    Entrypoint(
        "sharded_gnn.loss.ring.bucketed", _sharded_build("ring"),
        InvariantSpec(max_intermediate_bytes=HOT_BUDGET),
        notes="ring halo: per-block mask breaks the per-slice sorted "
              "promise, so no sorted-scatter expectation",
        cost=_RING_COST),
    Entrypoint(
        "gnn.forward.bucketed.pallas", _forward_pallas_build,
        InvariantSpec(forbid_primitives=NO_SET_SCATTER,
                      max_intermediate_bytes=HOT_BUDGET),
        notes="Pallas serving tier (settings.gnn_pallas): message passing "
              "runs inside pl.pallas_call, so no lax scatter exists to "
              "carry the sorted promise — expect_sorted_scatter stays off",
        cost=COST_DEFAULT),
    Entrypoint("streaming.rules_tick", _rules_tick_build, _TICK),
    Entrypoint(
        "ingest.delta_pack", _delta_pack_build,
        InvariantSpec(forbid_primitives=NO_SET_SCATTER,
                      max_intermediate_bytes=HOT_BUDGET),
        notes="graft-intake columnar staging: one staged int32 slab per "
              "tick sliced + bitcast into the tick's (ints, f_rows) on "
              "device — a single host→device transfer where the dict "
              "path paid two; zero FLOPs and zero collectives by "
              "contract (the ingest path may never go distributed or "
              "grow compute implicitly)",
        cost=COST_DEFAULT),
    Entrypoint("streaming.gnn_tick.bucketed", _gnn_tick_build, _TICK),
    Entrypoint(
        "streaming.gnn_tick.fused", _gnn_fused_tick_build,
        InvariantSpec(max_intermediate_bytes=FUSED_TICK_BUDGET),
        notes="graft-fuse: delta scatter → message pass → verdict in ONE "
              "pallas_call; [N, H] activations stay VMEM-resident across "
              "stages (the 4 MiB budget admits whole-table values and "
              "rejects [E, H]/[N, R, H] materializations); modeled HBM "
              "bytes/tick ratcheted STRICTLY below the composed tick's; "
              "explicit zero-collective CostSpec",
        cost=COST_DEFAULT),
    Entrypoint(
        "streaming.gnn_tick.fused.bf16",
        partial(_gnn_fused_tick_build, "bfloat16"),
        InvariantSpec(max_intermediate_bytes=FUSED_TICK_BUDGET,
                      bf16_accum_f32=True),
        notes="graft-tide: fused tick with bf16 matmul operands — every "
              "dot must still accumulate into f32 "
              "(preferred_element_type), same VMEM-resident budget as "
              "the f32 fused tick; zero-collective",
        cost=COST_DEFAULT),
    Entrypoint(
        "streaming.gnn_tick.dma", _gnn_dma_tick_build,
        InvariantSpec(max_intermediate_bytes=DMA_TICK_BUDGET),
        notes="graft-tide: beyond-VMEM tick at pn=N_NODES — edge "
              "mirror, features, and hidden state HBM-resident (ANY "
              "space), streamed through a double-buffered VMEM window "
              "by explicit async copies; the call-site stream model "
              "prices the dma_start tile traffic (bench pins it within "
              "1.25x of dma_tick_traffic_floor), fold order "
              "bit-identical to the resident fused tick; "
              "zero-collective",
        cost=COST_DEFAULT),
    Entrypoint(
        "streaming.gnn_tick.dma.bf16",
        partial(_gnn_dma_tick_build, "bfloat16"),
        InvariantSpec(max_intermediate_bytes=DMA_TICK_BUDGET),
        notes="graft-tide: DMA tick over a bfloat16 node-feature table "
              "— halves the streamed feature bytes; features upcast to "
              "f32 at the VMEM window, all accumulation f32; "
              "zero-collective",
        cost=COST_DEFAULT),
    Entrypoint(
        "streaming.gnn_tick.dma.int8",
        partial(_gnn_dma_tick_build, "int8"),
        InvariantSpec(max_intermediate_bytes=DMA_TICK_BUDGET),
        notes="graft-tide: DMA tick over an int8 node-feature table "
              "with per-column f32 scales (quarter feature bytes); "
              "delta rows arrive pre-quantized against the frozen "
              "scale, dequant + accumulate in f32; zero-collective",
        cost=COST_DEFAULT),
    Entrypoint(
        "ops.pallas_gms.vjp", _pallas_gms_vjp_build,
        InvariantSpec(forbid_primitives=NO_SET_SCATTER,
                      max_intermediate_bytes=PALLAS_VJP_BUDGET),
        notes="graft-fuse: grads through the Pallas gms custom_vjp — "
              "forward + transposed-layout dh kernel + per-relation "
              "[H, K] grad-matmul kernel; backward must stay tile-shaped "
              "(no [E_r, H] slice materialization) and zero-collective",
        cost=COST_DEFAULT),
    Entrypoint(
        "streaming.rules_tick.coalesced", _rules_tick_coalesced_build,
        _TICK,
        notes="queue-full adaptive coalescing merges pending deltas up to "
              "the top of the delta/row ladders (graft-pipeline); the "
              "merged tick must hold the same invariants and cost "
              "envelope as the steady-state tick — no silent FLOP/byte "
              "growth hiding in the coalesced shape",
        cost=COST_DEFAULT),
    Entrypoint(
        "streaming.gnn_tick.coalesced", _gnn_tick_coalesced_build, _TICK,
        notes="worst coalesced GNN tick (aux+edge deltas at the ladder "
              "top); explicit zero-collective CostSpec — the serving tick "
              "may never go distributed implicitly",
        cost=COST_DEFAULT),
    Entrypoint(
        "streaming.rules_tick.multitenant", _rules_tick_multitenant_build,
        _TICK,
        notes="graft-surge packed cross-tenant tick: SURGE_TENANTS "
              "regions on one resident state, every tenant's incidents "
              "scored in ONE pass of the stock donated _tick; byte/FLOP "
              "cost must stay exactly linear in the packed shapes and "
              "zero-collective (tenant packing adds no comms)",
        cost=COST_DEFAULT),
    Entrypoint(
        "streaming.rules_tick.sharded", _sharded_rules_tick_build, _TICK,
        notes="graft-fleet mesh-resident rules tick: per-shard routed "
              "deltas, owner-local evidence fold, verdicts reduced with "
              "ONE [rows, DIM+PW] psum — zero ppermutes, zero "
              "all-gathers; the ratchet pins halo traffic from day one",
        cost=_SHARDED_RULES_TICK_COST),
    Entrypoint(
        "streaming.rules_tick.elastic", _elastic_rules_tick_build, _TICK,
        notes="graft-swell elastic scale target: the sharded rules tick "
              "at D'=ELASTIC_SHARDS (one divisor-ladder rung up) — "
              "per-shard shapes shrink, the single [rows, DIM+PW] "
              "verdict psum stays byte-identical, zero ppermutes; "
              "pre-warmed by ElasticController.prewarm so a live scale "
              "event pays an upload, never a compile",
        cost=_ELASTIC_RULES_TICK_COST),
    Entrypoint(
        "streaming.gnn_tick.sharded", _sharded_gnn_tick_build, _TICK,
        notes="graft-fleet mesh-resident GNN tick: per-shard edge "
              "regions, ring-halo message pass — exactly "
              "(LAYERS+1)*GRAPH_SHARDS ppermutes of [N/D, H] blocks "
              "(LAYERS assembly rings + the readout ring), ZERO [N, H] "
              "all-gathers, zero psums; same contract as the snapshot "
              "ring kernels",
        cost=_SHARDED_GNN_TICK_COST),
    Entrypoint("ops.gather_matmul_segment", _gms_build(), _HOT),
    Entrypoint(
        "ops.gather_matmul_segment.bf16", _gms_build("bfloat16"),
        InvariantSpec(forbid_primitives=NO_SET_SCATTER,
                      max_intermediate_bytes=HOT_BUDGET,
                      expect_sorted_scatter=True, bf16_accum_f32=True)),
    Entrypoint(
        "ops.pallas_gather_matmul_segment", _pallas_gms_build(),
        InvariantSpec(forbid_primitives=NO_SET_SCATTER,
                      max_intermediate_bytes=PALLAS_TILE_BUDGET),
        notes="VMEM-tile byte budget: the [N, H] accumulator (1 MiB at "
              "the pallas canonical shapes) is the ceiling — any "
              "[E_r, H] slice-scale materialization (>= 4 MiB here) "
              "fails; explicit COST_DEFAULT pins zero collectives",
        cost=COST_DEFAULT),
    Entrypoint(
        "ops.pallas_gather_matmul_segment.bf16",
        _pallas_gms_build("bfloat16"),
        InvariantSpec(forbid_primitives=NO_SET_SCATTER,
                      max_intermediate_bytes=PALLAS_TILE_BUDGET,
                      bf16_accum_f32=True),
        notes="bf16 operands must still accumulate into f32 inside the "
              "kernel (preferred_element_type on the tile matmul)",
        cost=COST_DEFAULT),
    Entrypoint(
        "ops.k_hop_reach", _k_hop_build,
        InvariantSpec(forbid_primitives=NO_SET_SCATTER,
                      max_intermediate_bytes=HOT_BUDGET),
        notes="seed init is a dense one-hot, frontier scatter-max stays "
              "1-D per vmap lane"),
    Entrypoint(
        "ops.propagate_labels", _propagate_build,
        InvariantSpec(forbid_primitives=NO_SET_SCATTER,
                      max_intermediate_bytes=HOT_BUDGET)),
    Entrypoint(
        "rules.score_device", _score_device_build,
        InvariantSpec(max_intermediate_bytes=HOT_BUDGET),
        notes="dense evidence fold — no per-edge scatter at all; the "
              "static-index condition writes lower to 1-D set-scatters"),
    Entrypoint(
        "shield.snapshot_pack", _snapshot_pack_build,
        InvariantSpec(forbid_primitives=NO_SET_SCATTER,
                      max_intermediate_bytes=HOT_BUDGET),
        notes="graft-shield snapshot fetch: bitcast+concat the resident "
              "state into ONE int32 buffer (one device->host transfer "
              "per snapshot); recovery is pinned by the audit, not "
              "trusted — explicit zero-collective CostSpec",
        cost=COST_DEFAULT),
    Entrypoint(
        "heal.attest_fold", _attest_fold_build,
        InvariantSpec(forbid_primitives=NO_SET_SCATTER,
                      max_intermediate_bytes=HOT_BUDGET),
        notes="graft-heal per-shard state attestation: bitcast + "
              "wraparound-uint32 block sums over the node-addressed "
              "resident arrays, compared against the host-truth oracle "
              "at snapshot boundaries to localize silent per-shard "
              "corruption; zero dot FLOPs and an explicit "
              "zero-collective CostSpec at D=1 (sharded, the fold stays "
              "shard-local — no psum)",
        cost=COST_DEFAULT),
    Entrypoint(
        "shield.snapshot_unpack", _snapshot_unpack_build,
        InvariantSpec(forbid_primitives=NO_SET_SCATTER,
                      max_intermediate_bytes=HOT_BUDGET),
        notes="graft-shield restore: slice+bitcast the packed snapshot "
              "back into the resident buffers; zero collectives",
        cost=COST_DEFAULT),
)
