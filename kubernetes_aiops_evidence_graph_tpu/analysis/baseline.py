"""graft-cost — the ratcheted perf baseline.

``COST_BASELINE.json`` (repo root, committed) records the modeled cost of
every registered entrypoint at its canonical shapes: total FLOPs, HBM
bytes, peak live-intermediate bytes, and total collective payload bytes.
The check fails any entrypoint whose freshly-modeled numbers regress
beyond tolerance:

* FLOPs: **+2%** (``cost-flops``)
* HBM bytes and peak intermediate bytes: **+5%** (``cost-bytes``)
* collective payload bytes: **+5%** (``cost-collective-bytes``) — a zero
  baseline means ANY new collective traffic fails, so a single-device
  kernel cannot silently go distributed

plus bookkeeping rules that keep the baseline honest: every registered
(and traceable) entrypoint must have a baseline entry
(``cost-baseline-missing``) and every baseline entry must still be
registered (``cost-baseline-stale``). Improvements never fail — run
``--update-baseline`` to ratchet them in (that is also the workflow for
intentional regressions, reviewed via the diff of COST_BASELINE.json).

Waivers: an intentional regression carries
``# graft-audit: allow[cost] one-line reason`` on the line of (or
adjacent to) the entrypoint's name in the module that registers it.
Waived findings are counted and listed, never dropped — same policy as
the AST lint.
"""
from __future__ import annotations

import json
import re
from dataclasses import replace
from pathlib import Path

from .ast_lint import _WAIVER_RE, package_root
from .comms import check_collectives
from .findings import Finding

TOL_FLOPS = 0.02
TOL_BYTES = 0.05

# rules the allow[cost] pragma can waive
COST_RULES = frozenset({
    "cost-flops", "cost-bytes", "cost-collective-bytes",
    "cost-baseline-missing", "forbidden-collective", "collective-count",
    "collective-bytes",
})

_NAME_RE = re.compile(r'"([A-Za-z0-9_.\-]+)"')


def default_baseline_path() -> Path:
    return package_root().parent / "COST_BASELINE.json"


def load_baseline(path: Path) -> dict:
    """name -> baseline record; {} when the file does not exist yet."""
    if not Path(path).exists():
        return {}
    return json.loads(Path(path).read_text()).get("entrypoints", {})


def save_baseline(path: Path, entrypoints: dict) -> None:
    doc = {
        "tool": "graft-cost",
        "tolerances": {"flops": TOL_FLOPS, "bytes": TOL_BYTES},
        "entrypoints": {k: entrypoints[k] for k in sorted(entrypoints)},
    }
    Path(path).write_text(json.dumps(doc, indent=2) + "\n")


def baseline_record(cost) -> dict:
    """The ratcheted subset of an EntryCost (what the JSON commits)."""
    return {
        "flops": cost.flops,
        "dot_flops": cost.dot_flops,
        "hbm_bytes": cost.hbm_bytes,
        "peak_intermediate_bytes": cost.peak_intermediate_bytes,
        "collective_bytes": cost.collective_bytes,
    }


def cost_waivers(module, names) -> dict:
    """``# graft-audit: allow[cost] reason`` pragmas next to entrypoint
    registrations in ``module``'s source: name -> reason. The pragma must
    sit on the line of the quoted entrypoint name or an adjacent line."""
    try:
        lines = Path(module.__file__).read_text().splitlines()
    except (OSError, AttributeError):
        return {}
    pragmas: dict[int, str] = {}
    for i, line in enumerate(lines):
        m = _WAIVER_RE.search(line)
        if m and "cost" in {r.strip() for r in m.group(1).split(",")}:
            pragmas[i] = m.group(2).strip()
    waivers: dict[str, str] = {}
    if not pragmas:
        return waivers
    names = set(names)
    for i, line in enumerate(lines):
        for lit in _NAME_RE.findall(line):
            if lit not in names:
                continue
            for j in (i - 1, i, i + 1):
                if j in pragmas:
                    waivers[lit] = pragmas[j]
                    break
    return waivers


def _ratchet(name: str, label: str, rule: str, new: int, base: int,
             tol: float) -> "Finding | None":
    if new <= base * (1.0 + tol):
        return None
    pct = (new / base - 1.0) * 100 if base else float("inf")
    grew = f"+{pct:.1f}%" if base else f"{new} B/FLOPs from a zero baseline"
    return Finding(
        rule=rule, where=name, pass_name="cost",
        message=f"modeled {label} regressed: {new} vs baseline {base} "
                f"({grew}, tolerance +{tol * 100:.0f}%) — re-measure and "
                "run --update-baseline if intentional, or waive with "
                "# graft-audit: allow[cost]")


def check_against_baseline(costs: dict, baseline: dict,
                           registered_names) -> list[Finding]:
    """Ratchet every computed EntryCost against its baseline record."""
    findings: list[Finding] = []
    for name in sorted(costs):
        cost = costs[name]
        base = baseline.get(name)
        if base is None:
            findings.append(Finding(
                rule="cost-baseline-missing", where=name, pass_name="cost",
                message="no COST_BASELINE.json entry — run "
                        "--update-baseline to record this entrypoint"))
            continue
        for f in (
            _ratchet(name, "FLOPs", "cost-flops",
                     cost.flops, base.get("flops", 0), TOL_FLOPS),
            _ratchet(name, "HBM bytes", "cost-bytes",
                     cost.hbm_bytes, base.get("hbm_bytes", 0), TOL_BYTES),
            _ratchet(name, "peak intermediate bytes", "cost-bytes",
                     cost.peak_intermediate_bytes,
                     base.get("peak_intermediate_bytes", 0), TOL_BYTES),
            _ratchet(name, "collective bytes", "cost-collective-bytes",
                     cost.collective_bytes,
                     base.get("collective_bytes", 0), TOL_BYTES),
        ):
            if f is not None:
                findings.append(f)
    registered = set(registered_names)
    for name in sorted(set(baseline) - registered):
        findings.append(Finding(
            rule="cost-baseline-stale", where=name, pass_name="cost",
            message="baseline entry no longer matches any registered "
                    "entrypoint — run --update-baseline to drop it"))
    return findings


def _vs_baseline(cost, base: "dict | None") -> dict:
    if not base:
        return {}
    out = {}
    for key, new in baseline_record(cost).items():
        old = base.get(key, 0)
        out[key] = round(new / old - 1.0, 4) if old else (0.0 if not new
                                                          else None)
    return out


def run_cost_pass(entry_module=None, baseline_path=None,
                  update: bool = False):
    """Trace + cost + collective-check + ratchet the registered
    entrypoints. Returns ``(findings, cost_section)`` where
    ``cost_section`` is the JSON report's ``cost`` object.

    ``entry_module`` defaults to the built-in registry; fixture modules
    expose their own ``ENTRYPOINTS``. ``update=True`` rewrites the
    baseline (preserving entries for skipped/untraceable entrypoints)
    instead of ratcheting against it.
    """
    from .cost_model import cost_entrypoints
    if entry_module is None:
        from . import registry as entry_module
    entrypoints = entry_module.ENTRYPOINTS
    names = [e.name for e in entrypoints]

    costs, findings, skipped = cost_entrypoints(entrypoints)
    for entry in entrypoints:
        if entry.name in costs:
            findings.extend(check_collectives(
                entry.name, costs[entry.name],
                getattr(entry, "cost", None)))

    path = Path(baseline_path) if baseline_path else default_baseline_path()
    baseline = load_baseline(path)
    if update:
        merged = dict(baseline)
        for name in set(merged) - set(names):
            del merged[name]          # drop stale entries
        skipped_names = {s.split(" ", 1)[0] for s in skipped}
        for name in set(baseline) & skipped_names:
            merged[name] = baseline[name]   # keep what we could not trace
        for name, cost in costs.items():
            merged[name] = baseline_record(cost)
        save_baseline(path, merged)
        baseline = merged
    else:
        findings.extend(check_against_baseline(costs, baseline, names))

    waivers = cost_waivers(entry_module, names)
    findings = [
        replace(f, waived=True, waiver_reason=waivers[f.where])
        if f.rule in COST_RULES and f.where in waivers else f
        for f in findings
    ]

    section = {
        "baseline": str(path),
        "updated": bool(update),
        "tolerances": {"flops": TOL_FLOPS, "bytes": TOL_BYTES},
        "skipped": skipped,
        "entrypoints": {
            name: {**cost.to_dict(),
                   "vs_baseline": _vs_baseline(cost, baseline.get(name))}
            for name, cost in sorted(costs.items())
        },
    }
    return findings, section
