"""graft-audit CLI.

    python -m kubernetes_aiops_evidence_graph_tpu.analysis [--report json]
    python -m kubernetes_aiops_evidence_graph_tpu.analysis --cost
    python -m kubernetes_aiops_evidence_graph_tpu.analysis --update-baseline

Exit status 0 = zero unwaived violations; 1 = violations found. The
jaxpr pass traces the registered hot-path entrypoints (including both
sharded halo strategies, which need a multi-device mesh — a virtual
8-device CPU mesh is forced below when jax is not yet imported); the AST
pass lints the package source (or ``--root`` for fixture trees).

``--cost`` adds the graft-cost pass: a static roofline model (FLOPs, HBM
bytes, peak live-intermediate bytes, arithmetic intensity) plus a
collective-traffic census per entrypoint, ratcheted against the
committed ``COST_BASELINE.json`` (+2% FLOPs / +5% bytes tolerance; see
analysis/baseline.py). ``--update-baseline`` re-records the baseline
instead of ratcheting — commit the JSON diff for review.

Pass 4 (graft-sentinel, stdlib-only, on by default) adds the
concurrency & durability rules: use-after-donate dataflow, the
GUARDED_BY lock discipline + acquisition order, WAL/ledger
write-ahead-of-mutation dominance, and the Pallas DMA protocol
(see analysis/sentinel.py). ``--skip-sentinel`` disables it;
``--waivers`` lists every waiver pragma with its reason (a reason-less
waiver is a hard failure — the hygiene gate).

Pass 5 (graft-lattice, stdlib-only, on by default) pins the COMPILE
surface: the declared bucket-ladder registry and its shape contracts
(analysis/ladders.py), the retrace-hazard lint over the hot dirs
(analysis/retrace.py), and the dispatch-lattice enumeration + warm-
coverage proof (analysis/dispatch_lattice.py, analysis/warm_check.py).
``--skip-lattice`` disables it. The runtime half — the CompileFence
that attributes every post-warm compile under the chaos suites — is
env-gated via ``KAEG_COMPILE_FENCE=1`` (analysis/runtime_guards.py).

``--jaxpr-fixture dotted.module`` audits a module exposing an
``ENTRYPOINTS`` tuple instead of the built-in registry — how the
seeded-violation fixtures under tests/fixtures/audit are driven (with
``--cost-baseline`` pointing at a fixture baseline for the cost pass).
"""
from __future__ import annotations

import argparse
import importlib
import os
import sys


def _force_virtual_mesh() -> None:
    """8 virtual CPU devices so the sharded entrypoints trace hermetically
    (same discipline as tests/conftest.py). Must run before jax import."""
    if "jax" in sys.modules:
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graft-audit", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--report", choices=("text", "json"), default="text")
    ap.add_argument("--root", default=None,
                    help="lint this tree instead of the installed package "
                         "(fixture mode; skips the jaxpr pass unless "
                         "--jaxpr-fixture is also given)")
    ap.add_argument("--jaxpr-fixture", default=None,
                    help="dotted module exposing ENTRYPOINTS to audit "
                         "instead of the built-in registry")
    ap.add_argument("--skip-jaxpr", action="store_true")
    ap.add_argument("--skip-ast", action="store_true")
    ap.add_argument("--skip-sentinel", action="store_true",
                    help="skip pass 4 (concurrency & durability: "
                         "use-after-donate, lock/WAL discipline, DMA "
                         "protocol)")
    ap.add_argument("--skip-lattice", action="store_true",
                    help="skip pass 5 (compile surface: ladder "
                         "contracts, retrace hazards, dispatch-lattice "
                         "warm coverage)")
    ap.add_argument("--waivers", action="store_true",
                    help="list every `# graft-audit: allow[rule]` pragma "
                         "with its location, rules, and reason, then "
                         "exit (non-zero if any waiver has no reason)")
    ap.add_argument("--cost", action="store_true",
                    help="run the graft-cost pass (static roofline + "
                         "collective census, ratcheted against "
                         "COST_BASELINE.json)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="re-record COST_BASELINE.json from the current "
                         "traces instead of ratcheting (implies --cost)")
    ap.add_argument("--cost-baseline", default=None,
                    help="override the baseline JSON path (fixture mode)")
    args = ap.parse_args(argv)
    if args.update_baseline:
        args.cost = True

    if args.waivers:
        import json as _json

        from .sentinel import collect_waivers
        entries = collect_waivers(args.root)
        bare = [e for e in entries if not e["reason"]]
        if args.report == "json":
            print(_json.dumps({"waivers": entries,
                               "missing_reason": len(bare)}, indent=2))
        else:
            for e in entries:
                flag = "" if e["reason"] else "  <-- MISSING REASON"
                print(f"{e['where']} [{', '.join(e['rules'])}] "
                      f"{e['reason']}{flag}")
            print(f"graft-audit: {len(entries)} waiver(s), "
                  f"{len(bare)} missing a reason")
        return 1 if bare else 0

    from .findings import Report
    report = Report()

    run_jaxpr = not args.skip_jaxpr and (args.root is None
                                         or args.jaxpr_fixture)
    entry_module = None
    if run_jaxpr or args.cost:
        _force_virtual_mesh()
        import jax
        jax.config.update("jax_platforms", "cpu")
        if args.jaxpr_fixture:
            entry_module = importlib.import_module(args.jaxpr_fixture)
    if run_jaxpr:
        from .jaxpr_audit import audit_entrypoints
        if entry_module is not None:
            report.extend(audit_entrypoints(entry_module.ENTRYPOINTS))
        else:
            from .registry import ENTRYPOINTS
            report.extend(audit_entrypoints(ENTRYPOINTS))
    if not args.skip_ast:
        from .ast_lint import lint_tree
        report.extend(lint_tree(args.root))
    if not args.skip_sentinel:
        from .sentinel import run_sentinel
        report.extend(run_sentinel(args.root))
    if not args.skip_lattice:
        from .ladders import run_ladders
        from .retrace import run_retrace
        from .warm_check import run_warm_check
        report.extend(run_ladders(args.root))
        report.extend(run_retrace(args.root))
        report.extend(run_warm_check(args.root))
    if args.cost:
        from .baseline import run_cost_pass
        findings, section = run_cost_pass(
            entry_module=entry_module, baseline_path=args.cost_baseline,
            update=args.update_baseline)
        report.extend(findings)
        report.cost = section

    print(report.to_json() if args.report == "json" else report.to_text())
    return report.exit_code


if __name__ == "__main__":
    raise SystemExit(main())
