"""graft-cost — static collective-traffic contracts for sharded entrypoints.

The sharded halo strategies (parallel/sharded_gnn.py) were designed
around exact communication shapes: the ring path streams [N/D, H] blocks
with ``ppermute`` and must NEVER materialize a full [N, H] all-gather;
the allgather path performs exactly one all-gather per layer plus the
readout. Nothing in the qualitative audit pins that — an edit could add
a convenience ``all_gather`` to the ring loop and silently multiply
halo-exchange bytes by D without tripping any invariant.

Each registered entrypoint may carry a :class:`CostSpec` declaring its
expected collective census (exact counts per primitive, named bans,
per-op and total payload-byte ceilings). Entrypoints WITHOUT a spec get
:data:`COST_DEFAULT` — a single-device kernel must contain no
collectives at all. The census itself is computed by
cost_model.cost_jaxpr (loop-weighted: the ring's per-layer ``fori_loop``
lowers to a scan of length D, so its single traced ppermute counts D
times).

Rules: ``forbidden-collective`` (a banned primitive appears),
``collective-count`` (census differs from the declared exact count),
``collective-bytes`` (a single payload exceeds its per-op ceiling, or
the total exceeds ``max_total_bytes``). All are waivable with
``# graft-audit: allow[cost] reason`` next to the entrypoint
registration (see baseline.py).
"""
from __future__ import annotations

from dataclasses import dataclass, field

from .findings import Finding

# every cross-device communication primitive we census
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter",
})


@dataclass(frozen=True)
class CostSpec:
    """Declared collective-traffic contract for one entrypoint."""
    # primitive -> EXACT loop-weighted count the trace must contain
    expect_counts: dict = field(default_factory=dict)
    # primitives that must not appear at all
    forbid: tuple = ()
    # primitive -> max payload bytes any single op may move
    max_bytes_per_op: dict = field(default_factory=dict)
    # ceiling on total collective payload bytes for the whole trace
    max_total_bytes: "int | None" = None


# single-device kernels: no collectives, full stop
COST_DEFAULT = CostSpec(forbid=tuple(sorted(COLLECTIVE_PRIMS)))


def check_collectives(name: str, cost, spec: "CostSpec | None") -> list[Finding]:
    """Check one EntryCost's collective census against its CostSpec."""
    spec = spec if spec is not None else COST_DEFAULT
    findings: list[Finding] = []

    def hit(rule: str, message: str) -> None:
        findings.append(Finding(rule=rule, where=name, message=message,
                                pass_name="cost"))

    for prim in spec.forbid:
        rec = cost.collectives.get(prim)
        if rec and rec["count"]:
            hit("forbidden-collective",
                f"'{prim}' x{rec['count']} ({rec['bytes']} B payload) in a "
                "trace whose CostSpec bans it — e.g. a full-table gather "
                "sneaking into a ring halo")
    for prim, want in spec.expect_counts.items():
        got = cost.collectives.get(prim, {}).get("count", 0)
        if got != want:
            hit("collective-count",
                f"'{prim}' count {got} != declared {want} — the halo "
                "exchange structure drifted from its CostSpec")
    for prim, ceiling in spec.max_bytes_per_op.items():
        rec = cost.collectives.get(prim)
        if rec and rec["max_op_bytes"] > ceiling:
            hit("collective-bytes",
                f"'{prim}' moves {rec['max_op_bytes']} B in one op, over "
                f"the {ceiling} B per-op ceiling — a block grew beyond "
                "its declared [N/D, H] shape")
    if spec.max_total_bytes is not None \
            and cost.collective_bytes > spec.max_total_bytes:
        hit("collective-bytes",
            f"total collective payload {cost.collective_bytes} B exceeds "
            f"the {spec.max_total_bytes} B ceiling")
    return findings
