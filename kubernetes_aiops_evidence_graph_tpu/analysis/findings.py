"""Finding/Report model shared by the graft-audit passes.

A Finding is one rule hit at one location; a Report aggregates findings
across passes, separates waived sites (explicit ``# graft-audit:
allow[rule]`` pragmas) from violations, and serializes to the JSON shape
the CI artifact carries.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field

# Canonical rule-id registry: id -> (pass name, one-line description).
# EVERY Finding(rule=...) literal in analysis/ must resolve here (the
# drift guard in tests/test_graft_sentinel.py fails on a typo'd or
# undocumented id), and ``--report json`` embeds the table so the CI
# artifact is self-describing.
RULES: dict[str, tuple[str, str]] = {
    # pass 1 — jaxpr audit
    "trace-error": ("jaxpr", "entrypoint failed to trace at its canonical shapes"),
    "no-f64": ("jaxpr", "float64 intermediate in a hot-path jaxpr"),
    "forbidden-primitive": ("jaxpr", "primitive on the entrypoint's forbidden list"),
    "no-2d-scatter": ("jaxpr", "2-D scatter in the GNN hot path (PR 1 regression class)"),
    "byte-budget": ("jaxpr", "intermediate exceeds the per-entrypoint byte budget ([N,R,H]-scale materialization)"),
    "bf16-accum": ("jaxpr", "matmul accumulates in bf16 instead of f32"),
    "sorted-scatter-lost": ("jaxpr", "sorted-scatter contract lost (segment layout no longer (rel,dst)-sorted)"),
    # pass 2 — AST lint
    "syntax-error": ("ast", "file failed to parse"),
    "tracer-branch": ("ast", "Python branch on a traced value inside jitted code"),
    "np-in-traced": ("ast", "np.* call inside traced code (host per trace, constant-folds device data)"),
    "wall-clock": ("ast", "time.time() used for durations (non-monotonic under NTP steps)"),
    "host-sync": ("ast", "implicit device->host sync in a hot module"),
    "broad-except": ("ast", "broad except swallows all errors"),
    "recovery-no-broad-except": ("ast", "broad except in a recovery function that neither re-raises nor escalates"),
    "missing-static": ("ast", "int/bool-annotated jitted parameter not in static_argnames"),
    "jit-undeclared": ("ast", "hot-dir jit site missing from JIT_DECLARATIONS"),
    "jit-signature": ("ast", "jit site static/donate signature drifted from JIT_DECLARATIONS"),
    "tick-donation": ("ast", "resident-state tick entrypoint donates no buffers"),
    # pass 4 — graft-sentinel (concurrency & durability)
    "use-after-donate": ("sentinel", "value read/returned/stored after being passed in a donated position"),
    "lock-guard": ("sentinel", "GUARDED_BY attribute accessed outside a `with <lock>` scope"),
    "lock-order": ("sentinel", "nested lock acquisition violates the declared acquisition order"),
    "wal-order": ("sentinel", "resident-state mutation reachable before its WAL journal append"),
    "ledger-order": ("sentinel", "cluster mutation reachable before its intent-ledger row"),
    "dma-start-no-wait": ("sentinel", "async-copy start with no matching wait on the same semaphore"),
    "dma-wait-no-start": ("sentinel", "async-copy wait with no matching start on the same semaphore"),
    "dma-double-buffer": ("sentinel", "multiple DMA starts into one constant-indexed buffer slot (ping-pong lost)"),
    "dma-alias": ("sentinel", "aliased pallas_call site unregistered or its jit wrapper donates nothing"),
    "waiver-no-reason": ("sentinel", "# graft-audit: allow[...] pragma with no reason text"),
    # pass 5 — graft-lattice (compile-surface: ladders, retrace, warm)
    "ladder-gap": ("lattice", "bucket ladder violates a declared shape contract (non-monotone, gap ratio, or coverage without escalation)"),
    "ladder-divisibility": ("lattice", "ladder rung breaks a declared divisibility contract (tile/block alignment)"),
    "retrace-unbounded-static": ("lattice", "unquantized/unbounded value reaches a jit static argnum (one compile per distinct value)"),
    "retrace-weak-type": ("lattice", "bare Python number in a traced jit position (weak-type promotion mints a second executable)"),
    "warm-gap": ("lattice", "serve-reachable dispatch-lattice variant not covered by a verified warm path"),
    "lattice-unreachable": ("lattice", "declared tick entrypoint reachable by no settings combination (dead tier)"),
    # cost pass — graft-cost ratchet
    "cost-flops": ("cost", "modeled FLOPs regressed beyond the +2% ratchet"),
    "cost-bytes": ("cost", "modeled HBM/peak-intermediate bytes regressed beyond the +5% ratchet"),
    "cost-collective-bytes": ("cost", "modeled collective payload regressed beyond the +5% ratchet"),
    "cost-baseline-missing": ("cost", "entrypoint has no committed baseline row"),
    "cost-baseline-stale": ("cost", "baseline row for an entrypoint that no longer exists"),
    "forbidden-collective": ("cost", "collective primitive on the entrypoint's forbidden list"),
    "collective-count": ("cost", "more collectives per tick than the CostSpec permits"),
    "collective-bytes": ("cost", "collective payload exceeds the CostSpec ceiling"),
}


@dataclass(frozen=True)
class Finding:
    rule: str            # a key of RULES, e.g. "forbidden-primitive"
    where: str           # "path:line" (ast) or "entrypoint-name" (jaxpr)
    message: str
    pass_name: str       # "jaxpr" | "ast" | "runtime" | "sentinel" | "cost"
    waived: bool = False
    waiver_reason: str = ""

    def to_dict(self) -> dict:
        d = {"rule": self.rule, "where": self.where,
             "message": self.message, "pass": self.pass_name}
        if self.waived:
            d["waived"] = True
            d["waiver_reason"] = self.waiver_reason
        return d


@dataclass
class Report:
    findings: list[Finding] = field(default_factory=list)
    entrypoints_audited: list[str] = field(default_factory=list)
    # graft-cost section (per-entrypoint modeled costs + baseline deltas);
    # empty unless the cost pass ran
    cost: dict = field(default_factory=dict)

    def extend(self, other: "Report | list[Finding]") -> None:
        if isinstance(other, Report):
            self.findings.extend(other.findings)
            self.entrypoints_audited.extend(other.entrypoints_audited)
            if other.cost:
                self.cost = other.cost
        else:
            self.findings.extend(other)

    @property
    def violations(self) -> list[Finding]:
        return [f for f in self.findings if not f.waived]

    @property
    def waivers(self) -> list[Finding]:
        return [f for f in self.findings if f.waived]

    @property
    def exit_code(self) -> int:
        return 1 if self.violations else 0

    def to_dict(self) -> dict:
        d = {
            "tool": "graft-audit",
            "ok": not self.violations,
            "summary": {
                "violations": len(self.violations),
                "waived": len(self.waivers),
                "entrypoints_audited": len(self.entrypoints_audited),
            },
            "entrypoints": self.entrypoints_audited,
            "violations": [f.to_dict() for f in self.violations],
            "waived": [f.to_dict() for f in self.waivers],
            # self-describing artifact: the canonical rule table rides
            # along so a CI consumer can map ids without the source tree
            "rules": {rid: {"pass": p, "description": d}
                      for rid, (p, d) in sorted(RULES.items())},
        }
        if self.cost:
            d["cost"] = self.cost
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    def to_text(self) -> str:
        lines = []
        for f in self.violations:
            lines.append(f"VIOLATION [{f.pass_name}/{f.rule}] {f.where}: {f.message}")
        for f in self.waivers:
            lines.append(f"waived    [{f.pass_name}/{f.rule}] {f.where}: "
                         f"{f.waiver_reason or f.message}")
        for name, c in self.cost.get("entrypoints", {}).items():
            vs = c.get("vs_baseline") or {}
            flops_d = vs.get("flops")
            delta = (f" ({flops_d * 100:+.1f}% FLOPs vs baseline)"
                     if isinstance(flops_d, float) else "")
            lines.append(
                f"cost      {name}: {c['flops'] / 1e6:.1f} MFLOP, "
                f"{c['hbm_bytes'] / 1e6:.1f} MB HBM, "
                f"peak {c['peak_intermediate_bytes'] / 1e6:.1f} MB, "
                f"AI {c['arithmetic_intensity']:.2f}, "
                f"collectives {c['collective_bytes'] / 1e6:.2f} MB{delta}")
        if self.cost:
            lines.append(
                f"graft-cost: {len(self.cost.get('entrypoints', {}))} "
                f"entrypoint(s) modeled against {self.cost.get('baseline')}"
                + (" (baseline UPDATED)" if self.cost.get("updated") else ""))
        lines.append(
            f"graft-audit: {len(self.violations)} violation(s), "
            f"{len(self.waivers)} waived site(s), "
            f"{len(self.entrypoints_audited)} entrypoint(s) audited")
        return "\n".join(lines)
