"""Finding/Report model shared by the graft-audit passes.

A Finding is one rule hit at one location; a Report aggregates findings
across passes, separates waived sites (explicit ``# graft-audit:
allow[rule]`` pragmas) from violations, and serializes to the JSON shape
the CI artifact carries.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Finding:
    rule: str            # e.g. "forbidden-primitive", "broad-except"
    where: str           # "path:line" (ast) or "entrypoint-name" (jaxpr)
    message: str
    pass_name: str       # "jaxpr" | "ast" | "runtime"
    waived: bool = False
    waiver_reason: str = ""

    def to_dict(self) -> dict:
        d = {"rule": self.rule, "where": self.where,
             "message": self.message, "pass": self.pass_name}
        if self.waived:
            d["waived"] = True
            d["waiver_reason"] = self.waiver_reason
        return d


@dataclass
class Report:
    findings: list[Finding] = field(default_factory=list)
    entrypoints_audited: list[str] = field(default_factory=list)
    # graft-cost section (per-entrypoint modeled costs + baseline deltas);
    # empty unless the cost pass ran
    cost: dict = field(default_factory=dict)

    def extend(self, other: "Report | list[Finding]") -> None:
        if isinstance(other, Report):
            self.findings.extend(other.findings)
            self.entrypoints_audited.extend(other.entrypoints_audited)
            if other.cost:
                self.cost = other.cost
        else:
            self.findings.extend(other)

    @property
    def violations(self) -> list[Finding]:
        return [f for f in self.findings if not f.waived]

    @property
    def waivers(self) -> list[Finding]:
        return [f for f in self.findings if f.waived]

    @property
    def exit_code(self) -> int:
        return 1 if self.violations else 0

    def to_dict(self) -> dict:
        d = {
            "tool": "graft-audit",
            "ok": not self.violations,
            "summary": {
                "violations": len(self.violations),
                "waived": len(self.waivers),
                "entrypoints_audited": len(self.entrypoints_audited),
            },
            "entrypoints": self.entrypoints_audited,
            "violations": [f.to_dict() for f in self.violations],
            "waived": [f.to_dict() for f in self.waivers],
        }
        if self.cost:
            d["cost"] = self.cost
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    def to_text(self) -> str:
        lines = []
        for f in self.violations:
            lines.append(f"VIOLATION [{f.pass_name}/{f.rule}] {f.where}: {f.message}")
        for f in self.waivers:
            lines.append(f"waived    [{f.pass_name}/{f.rule}] {f.where}: "
                         f"{f.waiver_reason or f.message}")
        for name, c in self.cost.get("entrypoints", {}).items():
            vs = c.get("vs_baseline") or {}
            flops_d = vs.get("flops")
            delta = (f" ({flops_d * 100:+.1f}% FLOPs vs baseline)"
                     if isinstance(flops_d, float) else "")
            lines.append(
                f"cost      {name}: {c['flops'] / 1e6:.1f} MFLOP, "
                f"{c['hbm_bytes'] / 1e6:.1f} MB HBM, "
                f"peak {c['peak_intermediate_bytes'] / 1e6:.1f} MB, "
                f"AI {c['arithmetic_intensity']:.2f}, "
                f"collectives {c['collective_bytes'] / 1e6:.2f} MB{delta}")
        if self.cost:
            lines.append(
                f"graft-cost: {len(self.cost.get('entrypoints', {}))} "
                f"entrypoint(s) modeled against {self.cost.get('baseline')}"
                + (" (baseline UPDATED)" if self.cost.get("updated") else ""))
        lines.append(
            f"graft-audit: {len(self.violations)} violation(s), "
            f"{len(self.waivers)} waived site(s), "
            f"{len(self.entrypoints_audited)} entrypoint(s) audited")
        return "\n".join(lines)
