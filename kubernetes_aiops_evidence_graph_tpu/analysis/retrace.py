"""Pass 5 (graft-lattice), retrace half: AST lint for retrace hazards.

The zero-post-warm-compile SLO holds only if every value that reaches a
jit cache key is drawn from a small declared domain. Pass 2 pins the
*signatures* (static/donate declarations); this pass pins the *values*
flowing through them — the four hazard shapes that mint unplanned
compiles at serve time:

* ``retrace-unbounded-static`` —
  (a) a raw size expression (``len(...)``, ``.shape``) passed into a
  declared static argnum without going through a ladder quantizer
  (``bucket_for`` / ``rel_slice_offsets``): the cache key then tracks
  the live count, one compile per distinct value;
  (b) a ``str``/``dict``-annotated static parameter of a hot-dir jitted
  function with no entry in :data:`STATIC_DOMAINS` — an unbounded
  static domain is an unbounded executable cache;
  (c) a module-level array constant closure-captured inside a jitted
  function *and rebound elsewhere* — the capture bakes the array into
  the trace as a constant, so every rebind silently mints a fresh
  executable (constants assigned exactly once, like the baked rule
  tensors in rca/tpu_backend.py, are the sanctioned pattern and clean).
* ``retrace-weak-type`` — a bare Python numeric literal in a traced
  (non-static) position of a known jitted call: weak-type promotion
  gives the scalar a different aval than the same value arriving as a
  committed-dtype array, so call sites that mix the two retrace — pass
  ``jnp.asarray(x, dtype)`` or make the argument static.

Known jitted callables are the union of :data:`~.ast_lint.
JIT_DECLARATIONS` (the tree-wide registry) and the jit sites declared
in the same module (how fixture trees participate). Waivers follow the
standard ``# graft-audit: allow[rule] reason`` pragma. Stdlib-only —
part of the ``scripts/audit-fast.sh`` seconds-scale loop.
"""
from __future__ import annotations

import ast
from pathlib import Path

from .ast_lint import (HOT_DIRS, JIT_DECLARATIONS, _call_name,
                       _jit_decoration, _static_argnames_from_call,
                       package_root)
from .findings import Finding, Report
from .sentinel import _comment_waivers

# calls that map a raw count onto a declared ladder — an expression that
# passes through one of these is quantized, not unbounded
QUANTIZERS = {"bucket_for", "rel_slice_offsets"}

# declared value domains for string-typed statics: the dispatcher's
# compute/quant tiers. A str static NOT listed here has an unbounded
# domain — every new spelling is a new executable.
STATIC_DOMAINS: dict[str, tuple] = {
    "compute_dtype": (None, "bfloat16"),
    "feat_quant": ("", "bfloat16", "int8"),
}

# statics known tree-wide, keyed by bare function name
_DECLARED_STATICS: dict[str, set] = {}
for (_rel, _fn), (_statics, _donate) in JIT_DECLARATIONS.items():
    _DECLARED_STATICS.setdefault(_fn, set()).update(_statics)

_ARRAY_MAKER_PREFIXES = ("np.", "numpy.", "jnp.", "jax.numpy.")


def _is_array_maker(expr) -> bool:
    return (isinstance(expr, ast.Call)
            and _call_name(expr).startswith(_ARRAY_MAKER_PREFIXES))


def _size_flow(expr) -> str:
    """'quantized' | 'raw' | 'opaque' for a static-arg value expression."""
    raw = False
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            name = _call_name(n).rsplit(".", 1)[-1]
            if name in QUANTIZERS:
                return "quantized"
            if name == "len":
                raw = True
        elif isinstance(n, ast.Attribute) and n.attr in ("shape", "size"):
            raw = True
    return "raw" if raw else "opaque"


def _numeric_literal(expr) -> bool:
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.op,
                                                    (ast.USub, ast.UAdd)):
        expr = expr.operand
    return (isinstance(expr, ast.Constant)
            and type(expr.value) in (int, float))


class _FileRetrace:
    def __init__(self, path: Path, rel: str, source: str):
        self.rel = rel
        self.tree = ast.parse(source)
        self.findings: list[Finding] = []
        self.waivers = _comment_waivers(source)
        # local jit sites: name -> (statics, param order)
        self.local_jits: dict[str, tuple[set, tuple]] = {}
        call_form: dict[str, set] = {}
        for n in ast.walk(self.tree):
            if isinstance(n, ast.Call) and _call_name(n) in ("jax.jit",
                                                             "jit"):
                statics, _don = _static_argnames_from_call(n)
                if n.args and isinstance(n.args[0], ast.Name):
                    call_form[n.args[0].id] = statics
        for n in ast.walk(self.tree):
            if not isinstance(n, ast.FunctionDef):
                continue
            dec = _jit_decoration(n)
            statics = dec[0] if dec is not None else call_form.get(n.name)
            if statics is None:
                continue
            params = tuple(a.arg for a in list(n.args.args)
                           + list(n.args.kwonlyargs))
            self.local_jits[n.name] = (set(statics), params)
        # module-level array constants: name -> number of module-level binds
        self.array_binds: dict[str, int] = {}
        for node in self.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and _is_array_maker(node.value)):
                name = node.targets[0].id
                self.array_binds[name] = self.array_binds.get(name, 0) + 1
        # names rebound through `global` inside any function
        self.global_rebinds: set[str] = set()
        for n in ast.walk(self.tree):
            if not isinstance(n, ast.FunctionDef):
                continue
            declared_global = {g for s in ast.walk(n)
                               if isinstance(s, ast.Global)
                               for g in s.names}
            if not declared_global:
                continue
            for s in ast.walk(n):
                if isinstance(s, ast.Assign):
                    for t in s.targets:
                        if isinstance(t, ast.Name) \
                                and t.id in declared_global:
                            self.global_rebinds.add(t.id)

    def hit(self, rule: str, line: int, message: str) -> None:
        waived, reason = False, ""
        for ln in (line, line - 1):
            w = self.waivers.get(ln)
            if w and (rule in w[0] or "all" in w[0]):
                waived, reason = True, w[1]
                break
        self.findings.append(Finding(
            rule=rule, where=f"{self.rel}:{line}", message=message,
            pass_name="lattice", waived=waived, waiver_reason=reason))

    def _statics_of(self, bare: str) -> "set | None":
        if bare in self.local_jits:
            return self.local_jits[bare][0]
        return _DECLARED_STATICS.get(bare)

    def lint(self) -> list[Finding]:
        self._static_domains()
        self._call_sites()
        self._closure_capture()
        return self.findings

    # (b) unbounded static domains ------------------------------------
    def _static_domains(self) -> None:
        for n in ast.walk(self.tree):
            if not isinstance(n, ast.FunctionDef) \
                    or n.name not in self.local_jits:
                continue
            statics, _params = self.local_jits[n.name]
            for a in list(n.args.args) + list(n.args.kwonlyargs):
                ann = a.annotation
                if not (isinstance(ann, ast.Name)
                        and ann.id in ("str", "dict")):
                    continue
                if a.arg in statics and a.arg not in STATIC_DOMAINS:
                    self.hit(
                        "retrace-unbounded-static", n.lineno,
                        f"static parameter '{a.arg}: {ann.id}' of jitted "
                        f"'{n.name}' has no declared value domain "
                        "(analysis.retrace.STATIC_DOMAINS) — an unbounded "
                        "static domain is an unbounded executable cache")

    # (a) raw sizes into statics + weak-type literals ------------------
    def _call_sites(self) -> None:
        for n in ast.walk(self.tree):
            if not isinstance(n, ast.Call):
                continue
            bare = _call_name(n).rsplit(".", 1)[-1]
            statics = self._statics_of(bare)
            if statics is None:
                continue
            params = (self.local_jits[bare][1]
                      if bare in self.local_jits else None)
            for kw in n.keywords:
                if kw.arg is None:
                    continue
                if kw.arg in statics:
                    if _size_flow(kw.value) == "raw":
                        self.hit(
                            "retrace-unbounded-static", n.lineno,
                            f"raw size expression flows into static "
                            f"'{kw.arg}' of jitted '{bare}' without a "
                            "ladder quantizer (bucket_for / "
                            "rel_slice_offsets) — the jit cache key "
                            "tracks the live count, one compile per "
                            "distinct value")
                elif _numeric_literal(kw.value):
                    self.hit(
                        "retrace-weak-type", n.lineno,
                        f"bare Python number for traced argument "
                        f"'{kw.arg}' of jitted '{bare}': weak-type "
                        "promotion gives it a different aval than a "
                        "committed-dtype array — pass jnp.asarray(x, "
                        "dtype) or declare it static")
            for i, arg in enumerate(n.args):
                if not _numeric_literal(arg):
                    continue
                if params is not None and i < len(params) \
                        and params[i] in statics:
                    continue   # a static passed positionally: not traced
                self.hit(
                    "retrace-weak-type", n.lineno,
                    f"bare Python number in traced position {i} of "
                    f"jitted '{bare}': weak-type promotion gives it a "
                    "different aval than a committed-dtype array — pass "
                    "jnp.asarray(x, dtype) or declare it static")

    # (c) closure-captured arrays that get rebound ---------------------
    def _closure_capture(self) -> None:
        hazardous = {name for name, binds in self.array_binds.items()
                     if binds > 1 or name in self.global_rebinds}
        if not hazardous:
            return
        for n in ast.walk(self.tree):
            if not isinstance(n, ast.FunctionDef) \
                    or n.name not in self.local_jits:
                continue
            seen: set[str] = set()
            for s in ast.walk(n):
                if isinstance(s, ast.Name) \
                        and isinstance(s.ctx, ast.Load) \
                        and s.id in hazardous and s.id not in seen:
                    seen.add(s.id)
                    self.hit(
                        "retrace-unbounded-static", s.lineno,
                        f"jitted '{n.name}' closure-captures module "
                        f"array '{s.id}', which is rebound elsewhere — "
                        "each rebind bakes a fresh constant into the "
                        "trace and mints a new executable; pass it as "
                        "an operand (or never rebind it)")


def run_retrace(root: "Path | str | None" = None) -> Report:
    """Lint the hot dirs under ``root`` (default: installed package)."""
    base = Path(root) if root is not None else package_root()
    report = Report()
    for path in sorted(base.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel = path.relative_to(base).as_posix()
        if not set(Path(rel).parts[:-1]) & HOT_DIRS:
            continue
        try:
            fr = _FileRetrace(path, rel, path.read_text())
        except SyntaxError:
            continue    # pass 2 already reports syntax-error
        report.findings.extend(fr.lint())
    return report
