"""Declarative jaxpr invariants + the walker that enforces them.

The unit of policy is an :class:`InvariantSpec` attached to a registered
entrypoint (registry.py). The walker recurses through every sub-jaxpr
(pjit, scan, while, cond branches, shard_map, custom_* calls) so an
invariant holds for the WHOLE traced computation, not just the top level.

The invariants encode what PR 1 measured, not aesthetics:

* 2-D scatters (``scatter_dims_to_operand_dims`` rank >= 2) serialize on
  TPU — the scatter-bucket GNN variant measured 9.4x slower than the
  reference (rca/gnn.py module docstring); nothing may reintroduce one.
* a per-intermediate byte budget rejects any [N, R, H]-scale
  materialization — the dense transform-then-gather path writes+rereads
  151 MB/layer at the 50k bench config and held the reference to 7.8% of
  roofline.
* f64 anywhere means an accidental x64 upcast doubling HBM traffic.
* bf16 matmul operands must accumulate into f32
  (``preferred_element_type``) — one rounding per product term, never a
  bf16 running sum.
* host callbacks (pure/io/debug) in a hot kernel mean a device→host sync
  per dispatch.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from .findings import Finding

# every scatter-family primitive name (set/add/mul/min/max)
SCATTER_PRIMS = frozenset(
    {"scatter", "scatter-add", "scatter-mul", "scatter-min", "scatter-max"})
# host-callback primitives: any of these in a hot kernel is a per-dispatch
# device→host round trip
CALLBACK_PRIMS = frozenset({"pure_callback", "io_callback", "debug_callback"})
_F64_DTYPES = ("float64", "complex128")


@dataclass(frozen=True)
class InvariantSpec:
    """What one entrypoint's jaxpr must satisfy."""
    # primitive names that must not appear anywhere in the trace
    forbid_primitives: frozenset = CALLBACK_PRIMS
    # no scatter with >= 2 scatter_dims_to_operand_dims (TPU serializes)
    forbid_2d_scatter: bool = True
    # no float64/complex128 aval anywhere (accidental x64 creep)
    forbid_f64: bool = True
    # largest allowed per-eqn output intermediate, in bytes (None = unbounded);
    # sized to reject [N, R, H]-scale materialization at the canonical shapes
    max_intermediate_bytes: int | None = None
    # every dot_general with a bf16 operand must accumulate into f32
    bf16_accum_f32: bool = False
    # at least one scatter must carry indices_are_sorted=True (proves the
    # slices_sorted/sorted_by_dst promise actually reached the kernel)
    expect_sorted_scatter: bool = False


def _iter_sub_jaxprs(value):
    """Yield every Jaxpr reachable from one eqn param value."""
    if value is None:
        return
    if hasattr(value, "jaxpr") and hasattr(value.jaxpr, "eqns"):  # ClosedJaxpr
        yield value.jaxpr
    elif hasattr(value, "eqns"):                                  # raw Jaxpr
        yield value
    elif isinstance(value, (tuple, list)):
        for v in value:
            yield from _iter_sub_jaxprs(v)


def iter_eqns(jaxpr):
    """Depth-first over all equations of ``jaxpr`` and its sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn
        for pv in eqn.params.values():
            for sub in _iter_sub_jaxprs(pv):
                yield from iter_eqns(sub)


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * dtype.itemsize


def _scatter_index_rank(eqn) -> int:
    dn = eqn.params.get("dimension_numbers")
    dims = getattr(dn, "scatter_dims_to_operand_dims", ())
    return len(dims)


def check_jaxpr(name: str, closed_jaxpr, spec: InvariantSpec) -> list[Finding]:
    """Walk one traced entrypoint against its spec; one Finding per hit."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    findings: list[Finding] = []

    def hit(rule: str, message: str) -> None:
        findings.append(Finding(rule=rule, where=name, message=message,
                                pass_name="jaxpr"))

    if spec.forbid_f64:
        for v in list(jaxpr.invars) + list(jaxpr.constvars):
            dt = str(getattr(v.aval, "dtype", ""))
            if dt in _F64_DTYPES:
                hit("no-f64", f"{dt} input/const aval {v.aval}")

    saw_sorted_scatter = False
    peak_bytes, peak_desc = 0, ""
    for eqn in iter_eqns(jaxpr):
        prim = eqn.primitive.name
        if prim in spec.forbid_primitives:
            hit("forbidden-primitive", f"primitive '{prim}' is forbidden here")
        if prim in SCATTER_PRIMS:
            if eqn.params.get("indices_are_sorted"):
                saw_sorted_scatter = True
            if spec.forbid_2d_scatter and _scatter_index_rank(eqn) >= 2:
                hit("no-2d-scatter",
                    f"'{prim}' with scatter_dims_to_operand_dims="
                    f"{_scatter_index_rank(eqn)}-D index (TPU scatters "
                    "serialize; see rca/gnn.py — measured 9.4x slower)")
        for v in eqn.outvars:
            if spec.forbid_f64:
                dt = str(getattr(v.aval, "dtype", ""))
                if dt in _F64_DTYPES:
                    hit("no-f64", f"{dt} intermediate from '{prim}': {v.aval}")
            b = _aval_bytes(v.aval)
            if b > peak_bytes:
                peak_bytes, peak_desc = b, f"'{prim}' -> {v.aval}"
        if spec.bf16_accum_f32 and prim == "dot_general":
            in_dts = [str(getattr(v.aval, "dtype", "")) for v in eqn.invars]
            out_dt = str(eqn.outvars[0].aval.dtype)
            if "bfloat16" in in_dts and out_dt != "float32":
                hit("bf16-accum",
                    f"dot_general({'/'.join(in_dts)}) accumulates into "
                    f"{out_dt}; bf16 operands must accumulate into f32 "
                    "(preferred_element_type)")

    if (spec.max_intermediate_bytes is not None
            and peak_bytes > spec.max_intermediate_bytes):
        hit("byte-budget",
            f"largest intermediate {peak_bytes} B ({peak_desc}) exceeds the "
            f"{spec.max_intermediate_bytes} B budget — [N, R, H]-scale "
            "materialization in a bucketed path")
    if spec.expect_sorted_scatter and not saw_sorted_scatter:
        hit("sorted-scatter-lost",
            "no scatter carries indices_are_sorted=True although the "
            "layout promises a sorted fast path — the static flag is not "
            "reaching the kernel")
    return findings
