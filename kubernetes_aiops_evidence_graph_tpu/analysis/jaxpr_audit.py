"""Pass 1 — trace every registered entrypoint and walk its jaxpr.

Builders hand back (callable-with-statics-bound, args); tracing is
``jax.make_jaxpr`` — abstract evaluation only, so the canonical bench
shapes cost nothing to audit. A builder or trace failure is itself a
violation (rule ``trace-error``): a hot path that can no longer be traced
with its registered shapes is exactly the kind of silent drift this pass
exists to catch.
"""
from __future__ import annotations

from .findings import Finding, Report
from .invariants import check_jaxpr
from .registry import SkipEntrypoint


def trace_entrypoint(entry) -> "object":
    """Build + make_jaxpr one registry entry (statics already bound)."""
    import jax
    fn, args = entry.build()
    return jax.make_jaxpr(fn)(*args)


def audit_entrypoints(entrypoints) -> Report:
    report = Report()
    for entry in entrypoints:
        try:
            jaxpr = trace_entrypoint(entry)
        except SkipEntrypoint as exc:
            report.entrypoints_audited.append(f"{entry.name} (skipped: {exc})")
            continue
        except Exception as exc:  # graft-audit: allow[broad-except] any trace failure must surface as a finding, not crash the audit
            report.findings.append(Finding(
                rule="trace-error", where=entry.name,
                message=f"{type(exc).__name__}: {exc}", pass_name="jaxpr"))
            report.entrypoints_audited.append(f"{entry.name} (trace failed)")
            continue
        report.findings.extend(check_jaxpr(entry.name, jaxpr, entry.spec))
        report.entrypoints_audited.append(entry.name)
    return report


def audit_registered_entrypoints() -> Report:
    from .registry import ENTRYPOINTS
    return audit_entrypoints(ENTRYPOINTS)
