"""Pass 5 (graft-lattice): the reachable dispatch lattice, enumerated.

The serving stack dispatches across a multiplicative lattice of jitted
variants — backend tier (XLA / pallas / fused / dma) × quantization
(f32 / bf16 / int8) × graph shards × pipeline depth × bucket rung — and
the tier choice is made per dispatch by ``GnnStreamingScorer``'s gate
chain (``_dma_ok`` → ``_fused_ok`` → composed), then labeled by
``_tick_entrypoint`` with the registry name the cost model prices.
This module re-derives that mapping STATICALLY: it enumerates every
settings combination the serve path admits, resolves each to the
registry entrypoint the dispatcher would run, and hands warm_check the
reachable set to prove warm coverage over.

Two failure directions:

* ``lattice-unreachable`` — a tick-family entrypoint declared in
  :mod:`analysis.registry` that NO enumerated settings combination
  reaches: a dead tier that still costs audit/baseline maintenance and,
  worse, suggests the gate chain silently stopped selecting it.
* the reverse direction (a reachable point with no registered
  entrypoint or no warm coverage) is emitted by :mod:`.warm_check` as
  ``warm-gap``.

The enumeration mirrors the gate conditions in
``rca/gnn_streaming.py`` (kept honest by the mirror test in
tests/test_graft_lattice.py, which drives the REAL dispatcher through
every tier and asserts the resolved entry is in the enumerated set):

* sharded mirror (``serve_graph_shards > 1``) → the sharded tick,
  before any tier gate;
* DMA gate: ``gnn_tick_dma`` on, bucketed layout, compute dtype in
  {f32, bf16}, AND (a quantized feature tier is selected OR the
  resident fused tick's VMEM demand exceeds the budget);
* fused gate: ``gnn_fused_tick`` on, bucketed, compute in {f32, bf16};
* otherwise the composed tick (bucketed or not; ``gnn_pallas`` flips
  its kernel lowering, not its entrypoint identity).

The BUCKET-RUNG axis is deliberately not a per-point coordinate here:
rungs are proven discrete by the ladder half (ladders.py) and proven
warm at runtime by the CompileFence perf contract — the static lattice
covers variant identity, the runtime fence covers rung coverage.

The ``coalesced`` entries are the same executables at coalesced
top-rung delta shapes (the rung axis again), declared reachable via
:data:`RUNG_AXIS_VARIANTS`. The plain (un-bucketed) composed tick is a
parity/debug path — serve-reachable only by turning ``gnn_bucketed``
off, declared in :data:`OFF_SERVE_VARIANTS` with the reason.

Stdlib-only: :mod:`analysis.registry` imports no jax at module level,
so the fast audit loop stays seconds-scale.
"""
from __future__ import annotations

from dataclasses import dataclass
from itertools import product

from .findings import Finding, Report

# settings axes the serve path dispatches over (flag space, not shapes)
TIER_FLAGS = ("xla", "pallas", "fused", "dma")      # backend tier axis
QUANTS = ("", "bfloat16", "int8")                   # feature-quant axis
COMPUTE_DTYPES = (None, "bfloat16")                 # compute dtype axis
SHARDS = (1, 2)                                     # graph-shard axis
DEPTHS = (1, 2)                                     # pipeline-depth axis

# same-executable variants reached along the bucket-rung axis (coalesced
# churn ticks pack multiple event batches into one top-rung delta): the
# static lattice maps them to their base tier; the rung coverage itself
# is the CompileFence perf contract's job
RUNG_AXIS_VARIANTS = {
    "streaming.gnn_tick.coalesced": "streaming.gnn_tick.bucketed",
    "streaming.rules_tick.coalesced": "streaming.rules_tick",
    # the multi-tenant pack is the rules tick at PACK_BUCKETS rungs with
    # per-tenant row offsets — pack-rung axis of the same executable
    "streaming.rules_tick.multitenant": "streaming.rules_tick",
    # graft-swell: an elastic scale event re-lands the SAME sharded tick
    # executable at the target shard count D' — shard-count rung of the
    # sharded tier, pre-warmed through ElasticController.prewarm before
    # shield.scale_mesh adopts the mesh
    "streaming.rules_tick.elastic": "streaming.rules_tick.sharded",
}

# declared tiers that are reachable but NOT on the serve path (need an
# explicit settings flip a production config never makes); they are
# exempt from warm coverage but still must trace in the jaxpr audit
OFF_SERVE_VARIANTS = {
    # parity/debug: gnn_bucketed=False serves the reference composed
    # tick; production configs pin the bucketed ladder
    "streaming.gnn_tick": "gnn_bucketed=False parity/debug path",
}


@dataclass(frozen=True)
class LatticePoint:
    """One reachable point of the serve-time dispatch lattice."""
    tier: str          # "xla" | "pallas" | "fused" | "dma" | "sharded"
    compute: "str | None"   # compute dtype static (None = f32)
    quant: str         # feature-quant tier ("" = f32 features)
    shards: int
    depth: int
    entry: str         # registry entrypoint name the dispatcher labels

    @property
    def label(self) -> str:
        q = self.quant or "f32"
        c = "bf16" if self.compute == "bfloat16" else "f32"
        return (f"{self.entry}[tier={self.tier} compute={c} quant={q} "
                f"D={self.shards} depth={self.depth}]")


def resolve_entry(*, bucketed: bool, pallas: bool, fused: bool, dma: bool,
                  compute: "str | None", quant: str, sharded: bool,
                  vmem_over: bool) -> "tuple[str, str] | None":
    """(entrypoint, tier) the dispatcher would label for one settings
    combination — the static mirror of ``_tick_entrypoint`` +
    ``_dma_ok``/``_fused_ok``. None = the combination cannot serve
    (contradictory flags the constructor/gates refuse)."""
    if quant and not dma:
        return None        # a quant tier without the DMA tier never engages
    if sharded:
        return "streaming.gnn_tick.sharded", "sharded"
    if dma and bucketed and compute in (None, "bfloat16") \
            and (quant or vmem_over):
        if quant == "int8":
            return "streaming.gnn_tick.dma.int8", "dma"
        if quant == "bfloat16":
            return "streaming.gnn_tick.dma.bf16", "dma"
        return "streaming.gnn_tick.dma", "dma"
    if fused and bucketed and compute in (None, "bfloat16"):
        return ("streaming.gnn_tick.fused.bf16", "fused") \
            if compute == "bfloat16" \
            else ("streaming.gnn_tick.fused", "fused")
    if bucketed:
        return ("streaming.gnn_tick.bucketed",
                "pallas" if pallas else "xla")
    return "streaming.gnn_tick", "xla"


def enumerate_lattice() -> list[LatticePoint]:
    """Every serve-reachable lattice point (bucketed serve configs)."""
    points: set[LatticePoint] = set()
    for (pallas, fused, dma, compute, quant, shards, depth,
         vmem_over) in product(
            (False, True), (False, True), (False, True),
            COMPUTE_DTYPES, QUANTS, SHARDS, DEPTHS, (False, True)):
        resolved = resolve_entry(
            bucketed=True, pallas=pallas, fused=fused, dma=dma,
            compute=compute, quant=quant, sharded=shards > 1,
            vmem_over=vmem_over)
        if resolved is None:
            continue
        entry, tier = resolved
        points.add(LatticePoint(tier=tier, compute=compute, quant=quant,
                                shards=shards, depth=depth, entry=entry))
    # the base rules tick always serves alongside the GNN tick (the
    # fold that produces the verdict), sharded or not
    for shards, depth in product(SHARDS, DEPTHS):
        points.add(LatticePoint(
            tier="sharded" if shards > 1 else "xla", compute=None,
            quant="", shards=shards, depth=depth,
            entry="streaming.rules_tick.sharded" if shards > 1
            else "streaming.rules_tick"))
        points.add(LatticePoint(
            tier="xla", compute=None, quant="", shards=shards,
            depth=depth, entry="ingest.delta_pack"))
    return sorted(points, key=lambda p: (p.entry, p.shards, p.depth,
                                         str(p.compute), p.quant))


def reachable_entries() -> set[str]:
    return {p.entry for p in enumerate_lattice()}


def _declared_tick_entries() -> set[str]:
    """Tick-family entrypoint names the registry declares (module import
    is jax-free; builders pull jax lazily)."""
    from .registry import ENTRYPOINTS
    return {e.name for e in ENTRYPOINTS
            if e.name.startswith(("streaming.", "ingest."))}


def check_unreachable() -> list[Finding]:
    """``lattice-unreachable``: declared tick entrypoints no settings
    combination reaches."""
    declared = _declared_tick_entries()
    reached = reachable_entries()
    reached |= {v for v, base in RUNG_AXIS_VARIANTS.items()
                if base in reached}
    out: list[Finding] = []
    for name in sorted(declared):
        if name in reached or name in OFF_SERVE_VARIANTS:
            continue
        out.append(Finding(
            rule="lattice-unreachable", where=f"registry:{name}",
            message=f"declared tick entrypoint '{name}' is reachable by "
                    "no enumerated settings combination — a dead tier "
                    "still costs audit/baseline maintenance, or the "
                    "dispatcher's gate chain silently stopped selecting "
                    "it (update dispatch_lattice.resolve_entry or retire "
                    "the entry)", pass_name="lattice"))
    return out
