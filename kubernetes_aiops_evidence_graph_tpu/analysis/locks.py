"""graft-sentinel rule family 2 — ``lock-guard`` / ``lock-order``.

``lock-guard``: the :data:`GUARDED_BY` registry maps resident-state
attributes to the lock that owns them — the swap/heal generation seam
under ``serve_lock``, the warm re-arm flags under ``_warm_lock``. Any
access (read or write) to a guarded attribute outside a lexical ``with
<lock>:`` scope is a finding. Exemptions are explicit, not inferred:
``__init__`` (no concurrency before construction returns), the
``held_fns`` set (functions documented to run with the lock already held
— e.g. ``_swap_params_locked``), and the normal waiver pragma for
advisory reads whose race is argued harmless in the reason.

``lock-order``: nested acquisitions must follow the declared order —
the convention pinned by ``surge.swap_tenants_atomically``: coarse
container locks (a server's ``_lock``, the warm machinery's
``_warm_lock``) are acquired BEFORE any tenant/scorer ``serve_lock``,
never inside one. Acquiring an earlier-ranked lock while holding a
later-ranked one is the deadlock shape the runtime
:class:`~.runtime_guards.LockOrderGuard` hunts dynamically; this is the
static half.

Scope: lexical analysis only. ``with self.serve_lock:`` blocks are
recognized by the final attribute name of the context expression;
manual ``acquire()``/``release()`` choreography (the async tick seam)
is exempted via ``held_fns``. The held-set flows lexically into nested
function definitions (helpers defined and called inside the guarded
block). Fixture trees declare registries inline via ``GRAFT_SENTINEL``
(keys ``guarded_by``, ``held_fns``, ``lock_order``).
"""
from __future__ import annotations

import ast

from .ast_lint import _dotted

# rel path -> {"locks": {lock attr -> guarded attrs},
#              "held_fns": functions that run with the lock already held}
GUARDED_BY: dict[str, dict] = {
    # graft-evolve generation seam: swap/rollback/adopt flip the triple
    # under serve_lock; _swap_params_locked is the documented
    # already-held seam and dispatch runs under the tick caller's
    # serve_lock
    "rca/gnn_streaming.py": {
        "locks": {"serve_lock": {"_params", "_params_prev",
                                 "params_generation"}},
        "held_fns": {"_swap_params_locked", "_resident_arrays",
                     "_adopt_resident", "dispatch"},
    },
    # graft-heal bookkeeping: the exclusion set and heal generation move
    # only inside the scorer's serve_lock (mesh_heal / reexpand)
    "rca/shield.py": {
        "locks": {"serve_lock": {"_mesh_excluded", "_heal_gen",
                                 "_mesh_home"}},
        "held_fns": set(),
    },
    # graft-swell fleet state: tenant placement, per-tenant load EWMAs
    # and the scale/migration history ring are mutated by migrate()/
    # register() and read by the fleet API from HTTP threads
    "rca/surge.py": {
        "locks": {"_lock": {"_placement", "_loads", "_history"}},
        "held_fns": {"_place_locked", "_tenants_of_locked",
                     "_recover_placement", "_build_pack_locked"},
    },
    # warm re-arm machinery: the stop/re-arm flags are flipped from the
    # serve thread and read from the warm thread
    "rca/streaming.py": {
        "locks": {"_warm_lock": {"_warm_stop", "_warm_rearm_pending",
                                 "_warm_active"}},
        "held_fns": set(),
    },
}

# rel path -> acquisition order (earlier entries must be taken first);
# the swap_tenants_atomically convention: container locks before any
# scorer serve_lock
LOCK_ORDER: dict[str, tuple[str, ...]] = {
    "rca/surge.py": ("_lock", "serve_lock"),
    "rca/streaming.py": ("_warm_lock", "serve_lock"),
    "rca/shield.py": ("_lock", "serve_lock"),
}


def _config(sf):
    cfg = GUARDED_BY.get(sf.rel, {})
    locks = {k: set(v) for k, v in cfg.get("locks", {}).items()}
    held_fns = set(cfg.get("held_fns", ()))
    for lock, attrs in sf.inline.get("guarded_by", {}).items():
        locks.setdefault(lock, set()).update(attrs)
    held_fns.update(sf.inline.get("held_fns", ()))
    order = tuple(sf.inline.get("lock_order", ())) \
        or LOCK_ORDER.get(sf.rel, ())
    return locks, held_fns, order


class _LockWalk:
    def __init__(self, sf, locks: dict, held_fns: set, order: tuple):
        self.sf, self.locks, self.held_fns, self.order = \
            sf, locks, held_fns, order
        self.attr_to_lock = {a: lk for lk, attrs in locks.items()
                             for a in attrs}
        self.known = set(locks) | set(order)

    def run(self) -> None:
        for node in self.sf.tree.body:
            self.walk(node, held=frozenset(), exempt=False)

    def walk(self, node, held: frozenset, exempt: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name == "__init__" or node.name in self.held_fns:
                exempt = True
            for child in node.body:
                self.walk(child, held, exempt)
            return
        if isinstance(node, ast.With):
            acquired = []
            for item in node.items:
                name = _dotted(item.context_expr).rsplit(".", 1)[-1]
                if name in self.known:
                    self.check_order(name, held, item.context_expr.lineno)
                    acquired.append(name)
                else:
                    self.visit_exprs(item.context_expr, held, exempt)
            inner = held.union(acquired)
            for child in node.body:
                self.walk(child, inner, exempt)
            return
        self.visit_exprs(node, held, exempt)

    def visit_exprs(self, node, held: frozenset, exempt: bool) -> None:
        """Flag guarded-attribute accesses; recurse through compound
        statements so nested With blocks keep extending the held set."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.With)):
                self.walk(child, held, exempt)
                continue
            if isinstance(child, ast.Attribute):
                lock = self.attr_to_lock.get(child.attr)
                if lock is not None and lock not in held and not exempt:
                    self.sf.hit(
                        "lock-guard", child.lineno,
                        f"'{child.attr}' is guarded by '{lock}' "
                        f"(GUARDED_BY) but accessed outside a `with "
                        f"{lock}` scope — torn reads/lost updates across "
                        "the serve/swap seam; hold the lock, move the "
                        "access into a held_fns seam, or waive an "
                        "advisory read with the race argument")
            self.visit_exprs(child, held, exempt)

    def check_order(self, name: str, held: frozenset, line: int) -> None:
        if name not in self.order:
            return
        rank = self.order.index(name)
        for h in held:
            if h in self.order and self.order.index(h) > rank:
                self.sf.hit(
                    "lock-order", line,
                    f"'{name}' acquired while holding '{h}' inverts the "
                    f"declared order {self.order} (the "
                    "swap_tenants_atomically convention: container locks "
                    "before scorer serve_locks) — this is the static "
                    "half of the deadlock-cycle guard")


def check(sf) -> None:
    locks, held_fns, order = _config(sf)
    if not locks and not order:
        return
    _LockWalk(sf, locks, held_fns, order).run()
