"""Pass 4 — graft-sentinel: concurrency & durability static analysis.

The serving stack is a heavily concurrent, crash-consistent system —
donated resident buffers, WAL-before-mutate shield discipline,
intent-before-mutation remediation ledgers, swap/heal generation
boundaries under ``serve_lock``, and double-buffered ``dma_start`` /
``dma_wait`` Pallas streaming — and until this pass every one of those
invariants was enforced only by convention and replay tests. This module
is the shared driver: it parses each source file once, hands the
:class:`SentinelFile` to the four rule-family checkers, and adds the
waiver-hygiene gate.

Rule families (each in its own module):

* :mod:`.donation`  — ``use-after-donate``: intraprocedural dataflow over
  the hot dirs; a value passed in a donated position of a jitted call
  must not be read, returned, or stored afterwards on any path.
* :mod:`.locks`     — ``lock-guard`` / ``lock-order``: the
  :data:`~.locks.GUARDED_BY` registry maps resident-state attributes to
  their lock; accesses outside a ``with <lock>`` scope fail, and nested
  acquisitions must follow the declared order (the
  ``surge.swap_tenants_atomically`` convention).
* :mod:`.ordering`  — ``wal-order`` / ``ledger-order``: registered
  mutation calls must be dominated by the matching journal-append /
  intent-row call in the same function (WAL-before-mutate).
* :mod:`.dma_check` — ``dma-start-no-wait`` / ``dma-wait-no-start`` /
  ``dma-double-buffer`` / ``dma-alias``: Pallas kernel DMA protocol and
  ``input_output_aliases``-vs-donation consistency.

Plus the hygiene gate here: ``waiver-no-reason`` — every ``# graft-audit:
allow[rule]`` pragma must carry a reason; a bare waiver is a hard
failure (it is also the one rule that cannot itself be waived).

Fixture trees (and, if ever needed, real modules) can extend the central
registries inline with a module-level literal::

    GRAFT_SENTINEL = {
        "guarded_by": {"serve_lock": ["_params"]},
        "held_fns": ["_swap_locked"],
        "lock_order": ["outer_lock", "inner_lock"],
        "ordering": {"rule": "wal-order", "journal": ["append"],
                     "mutate": ["apply"], "exempt": "replay|recover"},
        "dma_alias": {"fn_name": "scratch"},   # or ["rel/path.py", "fn"]
    }

This pass is stdlib-only (never imports jax) so ``scripts/audit-fast.sh``
stays a seconds-scale pre-push loop.
"""
from __future__ import annotations

import ast
import io
import tokenize
from pathlib import Path

from .ast_lint import _WAIVER_RE, HOT_DIRS, package_root
from .findings import Finding, Report


def _comment_waivers(source: str) -> dict[int, tuple[set, str]]:
    """line -> (rules, reason) for every waiver pragma in a REAL comment
    token — docstrings quoting the pragma syntax don't count."""
    out: dict[int, tuple[set, str]] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = _WAIVER_RE.search(tok.string)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")}
                out[tok.start[0]] = (rules, m.group(2).strip())
    except (tokenize.TokenError, IndentationError):
        pass
    return out


class SentinelFile:
    """One parsed source file shared by the four checkers."""

    def __init__(self, path: Path, rel: str, source: str):
        self.path, self.rel, self.source = path, rel, source
        self.tree = ast.parse(source)
        self.findings: list[Finding] = []
        self.in_hot = bool(set(Path(rel).parts[:-1]) & HOT_DIRS)
        self.waivers = _comment_waivers(source)
        self.inline = self._inline_registry()

    def _inline_registry(self) -> dict:
        """Module-level ``GRAFT_SENTINEL = {...}`` literal (fixtures)."""
        for node in self.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "GRAFT_SENTINEL"):
                try:
                    value = ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    return {}
                return value if isinstance(value, dict) else {}
        return {}

    def hit(self, rule: str, line: int, message: str,
            waivable: bool = True) -> None:
        waived, reason = False, ""
        if waivable:
            for ln in (line, line - 1):
                w = self.waivers.get(ln)
                if w and (rule in w[0] or "all" in w[0]):
                    waived, reason = True, w[1]
                    break
        self.findings.append(Finding(
            rule=rule, where=f"{self.rel}:{line}", message=message,
            pass_name="sentinel", waived=waived, waiver_reason=reason))


def collect_waivers(root: "Path | str | None" = None) -> list[dict]:
    """Every waiver pragma under ``root`` — the ``--waivers`` CLI mode."""
    base = Path(root) if root is not None else package_root()
    out: list[dict] = []
    for path in sorted(base.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel = path.relative_to(base).as_posix()
        for i, (rules, reason) in sorted(
                _comment_waivers(path.read_text()).items()):
            out.append({"where": f"{rel}:{i}",
                        "rules": sorted(rules),
                        "reason": reason})
    return out


def _waiver_hygiene(sf: SentinelFile) -> None:
    """``waiver-no-reason``: a bare pragma silently hides a rule with no
    recorded justification — hard failure, never itself waivable."""
    for line, (rules, reason) in sorted(sf.waivers.items()):
        if not reason:
            sf.hit("waiver-no-reason", line,
                   f"waiver for [{', '.join(sorted(rules))}] carries no "
                   "reason — `# graft-audit: allow[rule] why` is the "
                   "contract; a bare allow hides the rule with no "
                   "recorded justification", waivable=False)


def run_sentinel(root: "Path | str | None" = None) -> Report:
    """Run the four sentinel checkers + waiver hygiene over ``root``
    (default: the installed package)."""
    from . import dma_check, donation, locks, ordering
    base = Path(root) if root is not None else package_root()
    report = Report()
    for path in sorted(base.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel = path.relative_to(base).as_posix()
        try:
            sf = SentinelFile(path, rel, path.read_text())
        except SyntaxError:
            continue    # pass 2 already reports syntax-error
        donation.check(sf)
        locks.check(sf)
        ordering.check(sf)
        dma_check.check(sf)
        _waiver_hygiene(sf)
        report.findings.extend(sf.findings)
    return report
