"""graft-sentinel rule family 3 — ``wal-order`` / ``ledger-order``.

Crash consistency in this codebase is one invariant wearing two hats:
the durable record of an intent hits disk BEFORE the mutation it
describes. The shield WAL-journals every delta batch, params swap, and
mesh heal before applying it (``wal-order``); the remediation executor
writes an intent row before dispatching a cluster mutation
(``ledger-order``). Replay correctness depends on the order — a
mutation that can execute before its record means a crash in the gap
replays into a state that never existed.

The checker is a per-function must-dominance analysis over the
:data:`ORDERED_SITES` registry: every call whose trailing name is in the
file's ``mutate`` set must be dominated — reached on EVERY path — by a
call matching the ``journal`` suffix earlier in the same function.

Mechanics:

* statements execute in source order; a journal call flips the
  "journaled" fact for everything after it (and for later calls in the
  same statement, by position);
* ``if``/``else`` merge by AND over the branches that fall through
  (a branch ending in ``return``/``raise`` does not reach the merge);
* the **vacuous-empty** special case: ``if recs: journal.append(recs)``
  with no ``else`` — when the test is a bare name, the implicit else
  means the batch is empty, so the un-journaled path mutates nothing;
  the merge keeps "journaled";
* loop bodies may run zero times, so a journal inside a loop never
  satisfies a mutation after it;
* functions whose name matches the file's ``exempt`` regex (replay /
  recovery / reconcile paths, which re-apply already-durable records)
  are skipped entirely.

Journal AND mutate matching are by dotted-suffix (``journal.append``
will not match a ``list.append``; ``s.rollback_params`` matches the
scorer-level mutation but not the shield's own journaling wrapper
``self.rollback_params``). Fixture trees declare the registry inline via
``GRAFT_SENTINEL["ordering"]``.
"""
from __future__ import annotations

import ast
import re

from .ast_lint import _call_name

# rel path -> {rule, journal (dotted suffixes), mutate (trailing names),
#              exempt (regex over function names)}
ORDERED_SITES: dict[str, dict] = {
    # WAL-before-mutate (graft-shield): delta batches, params swaps,
    # heals, and re-expansions journal (fsync) before the scorer mutates
    # `s` is the shield's scorer handle — the suffixes name the
    # scorer-level mutation seams, not the shield's journaling wrappers
    "rca/shield.py": {
        "rule": "wal-order",
        "journal": ("journal.append",),
        "mutate": ("s._apply_records", "s._apply_edge_records",
                   "s.swap_params", "s.rollback_params", "s.adopt_mesh",
                   "s._swap_params_locked"),
        "exempt": r"replay|recover|restore|reconcile|rebuild",
    },
    # the atomic multi-tenant swap journals each shielded tenant before
    # installing the generation through its locked seam; graft-swell
    # migration likewise appends the fleet-WAL intent record before the
    # source repack / destination adopt mutate either pack
    "rca/surge.py": {
        "rule": "wal-order",
        "journal": ("journal.append",),
        "mutate": ("scorer._swap_params_locked",
                   "pack.remove_tenant", "pack.add_tenant"),
        "exempt": r"replay|recover|restore",
    },
    # intent-before-mutation (graft-saga): the executor writes the
    # intent row before any cluster dispatch; _reconcile probes in-doubt
    # intents and is the sanctioned re-fire path
    "remediation/executor.py": {
        "rule": "ledger-order",
        "journal": ("execution_intent",),
        "mutate": ("self._dispatch_one",),
        "exempt": r"reconcile|replay|recover",
    },
}


class _Dominance:
    def __init__(self, sf, cfg: dict):
        self.sf = sf
        self.rule = cfg["rule"]
        self.journal = tuple(cfg["journal"])
        self.mutate = set(cfg["mutate"])

    def run(self, fn: ast.FunctionDef) -> None:
        self.block(fn.body, journaled=False)

    def block(self, stmts, journaled: bool) -> tuple[bool, bool]:
        """Returns (journaled-at-exit, definitely-terminated)."""
        for stmt in stmts:
            journaled, terminated = self.stmt(stmt, journaled)
            if terminated:
                return journaled, True
        return journaled, False

    def stmt(self, stmt, journaled: bool) -> tuple[bool, bool]:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return journaled, False    # nested defs: own analysis pass
        if isinstance(stmt, (ast.Return, ast.Raise)):
            self.calls(stmt, journaled)
            return journaled, True
        if isinstance(stmt, ast.If):
            self.calls(stmt.test, journaled)
            j_b, t_b = self.block(stmt.body, journaled)
            j_e, t_e = self.block(stmt.orelse, journaled)
            if t_b and t_e:
                return True, True
            if t_b:
                return j_e, False
            if t_e:
                return j_b, False
            if (not stmt.orelse and j_b
                    and isinstance(stmt.test, ast.Name)):
                # vacuous-empty: `if recs: journal.append(recs)` — the
                # implicit else carries an empty batch, so the
                # un-journaled path mutates nothing
                return True, False
            return j_b and j_e, False
        if isinstance(stmt, (ast.For, ast.While)):
            self.calls(getattr(stmt, "iter", None)
                       or getattr(stmt, "test", None), journaled)
            self.block(stmt.body, journaled)
            j, _t = self.block(stmt.orelse, journaled)
            return j, False            # body may run zero times
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                journaled = self.calls(item.context_expr, journaled)
            return self.block(stmt.body, journaled)
        if isinstance(stmt, ast.Try):
            j_b, t_b = self.block(stmt.body, journaled)
            outs = [] if t_b else [j_b]
            for h in stmt.handlers:
                # the exception may fire before the journal call landed
                j_h, t_h = self.block(h.body, journaled)
                if not t_h:
                    outs.append(j_h)
            if not t_b:
                j_o, t_o = self.block(stmt.orelse, j_b)
                if stmt.orelse and not t_o:
                    outs[0] = j_o
            merged = bool(outs) and all(outs)
            j_f, t_f = self.block(stmt.finalbody, merged)
            return (j_f if stmt.finalbody else merged), t_f
        return self.calls(stmt, journaled), False

    def calls(self, node, journaled: bool) -> bool:
        """Process every call in source order; flag un-dominated
        mutations, absorb journal appends."""
        if node is None:
            return journaled
        found = [n for n in ast.walk(node) if isinstance(n, ast.Call)]
        for call in sorted(found, key=lambda c: (c.lineno, c.col_offset)):
            dotted = _call_name(call)
            if any(dotted.endswith(suffix) for suffix in self.journal):
                journaled = True
            elif any(dotted.endswith(suffix) for suffix in self.mutate) \
                    and not journaled:
                trailing = dotted.rsplit(".", 1)[-1]
                self.sf.hit(
                    self.rule, call.lineno,
                    f"mutation '{trailing}' is reachable before its "
                    f"{'intent row' if self.rule == 'ledger-order' else 'WAL append'}"
                    f" ({' / '.join(self.journal)}) — the durable record "
                    "must hit disk first or a crash in the gap replays "
                    "into a state that never existed")
        return journaled


def check(sf) -> None:
    cfg = ORDERED_SITES.get(sf.rel) or sf.inline.get("ordering")
    if not cfg:
        return
    exempt = re.compile(cfg.get("exempt") or r"$^")
    dom = _Dominance(sf, cfg)
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.FunctionDef) \
                and not exempt.search(node.name):
            dom.run(node)
