"""graft-audit — static analysis that pins the TPU hot-path invariants.

PR 1 bought its speedup by structural invariants (no 2-D scatters in the
GNN hot path, no dense [N, R, H] materialization, static (rel, dst)-sorted
slice layouts, bf16-operand/f32-accum dtype discipline) that nothing
guarded: one careless edit to rca/gnn.py or parallel/sharded_gnn.py would
silently reintroduce the 41 ms/forward regression. This subsystem encodes
those properties as machine-checkable analysis instead of tribal
knowledge — the reference system's core value is *auditability* of
automated decisions (PAPERS.md), and that has to include our own compute
graph.

Three passes:

* **Pass 1 — jaxpr audit** (`jaxpr_audit`, `registry`, `invariants`):
  every hot-path entrypoint (bucketed + reference GNN forward, both
  sharded halo strategies, the streaming ticks, ops kernels, the rules
  scoring kernel, the train step) is traced with canonical bench shapes
  and its jaxpr walked against a declarative invariant spec — forbidden
  primitives, no 2-D scatter, no f64, a per-intermediate byte budget that
  rejects [N, R, H]-scale materialization, bf16→f32 accumulation on the
  matmul paths, and the sorted-scatter contract.
* **Pass 2 — AST lint** (`ast_lint`): repo-specific source rules —
  tracer branches and np./wall-clock calls inside jitted code, implicit
  host syncs in the hot modules, broad excepts, and jit static/donate
  signature completeness — with an inline ``# graft-audit: allow[rule]``
  waiver pragma so intentional sites are explicit and counted.
* **Pass 3 — runtime guards** (`runtime_guards`): pytest-side transfer
  guards + a compilation counter for recompilation-hazard detection on
  the streaming-churn workload (see tests/test_graft_audit.py), plus the
  opt-in :class:`~.runtime_guards.LockOrderGuard` (env
  ``KAEG_LOCK_ORDER_GUARD=1``) that records lock-acquisition order under
  the chaos suites and fails on an observed ordering cycle.
* **Pass 4 — graft-sentinel** (`sentinel`, `donation`, `locks`,
  `ordering`, `dma_check`): concurrency & durability — use-after-donate
  dataflow over the hot dirs, the GUARDED_BY lock-discipline registry +
  static acquisition order, WAL/ledger write-ahead-of-mutation dominance
  (shield.py / remediation), the Pallas DMA start/wait + aliasing
  protocol, and the waiver-hygiene gate (every ``allow[...]`` pragma
  must carry a reason). Stdlib-only, so ``scripts/audit-fast.sh`` (AST +
  sentinel, no tracing) stays a seconds-scale pre-push loop.
* **Pass 5 — graft-lattice** (`ladders`, `retrace`,
  `dispatch_lattice`, `warm_check`): the COMPILE surface — the single
  declared registry of every bucket ladder with its shape contracts
  (monotone rungs, bounded gap ratios, tile/block divisibility,
  coverage-to-500k-pods or a declared escalation), an AST lint for the
  retrace hazards that mint unplanned executables (raw sizes into
  static argnums, unbounded static domains, weak-type scalar
  promotion, rebound closure-captured arrays), and the dispatch-lattice
  proof: enumerate every serve-reachable tick variant (tier × quant ×
  shards) and verify each is pre-compiled by a warm path that goes
  through the SAME dispatch seam serving uses. The runtime half is the
  env-gated :class:`~.runtime_guards.CompileFence`
  (``KAEG_COMPILE_FENCE=1``), which attributes every post-warm compile
  under the chaos suites to a lattice point and fails on any stray.
  Stdlib-only, so it rides in ``scripts/audit-fast.sh``.
* **graft-cost** (`cost_model`, `comms`, `baseline`, ``--cost``): the
  QUANTITATIVE dimension — a static roofline model per entrypoint
  (per-primitive FLOPs, HBM read/write bytes from operand/result avals,
  peak live-intermediate bytes, arithmetic intensity), a collective
  census checked against each entrypoint's declared
  :class:`~.comms.CostSpec` (the ring halo must stream [N/D, H]
  ``ppermute`` blocks and contain zero full-[N, H] all-gathers), and a
  ratchet against the committed ``COST_BASELINE.json`` (+2% FLOPs / +5%
  bytes tolerance; ``--update-baseline`` re-records, ``# graft-audit:
  allow[cost]`` waives an intentional regression).

CLI: ``python -m kubernetes_aiops_evidence_graph_tpu.analysis --report
json`` exits non-zero on violations; add ``--cost`` for the ratchet.
This package must stay import-light (no jax at import time) — passes 1
and 4 pull jax lazily.
"""
from __future__ import annotations

from .findings import Finding, Report

__all__ = ["Finding", "Report", "run_audit"]


def run_audit(root=None, jaxpr: bool = True, ast: bool = True,
              cost: bool = False, sentinel: bool = True,
              lattice: bool = True) -> Report:
    """Run the static passes and return a combined Report.

    ``root`` overrides the source tree for the AST, sentinel, and
    lattice passes (fixture trees in tests); the jaxpr pass always
    audits the installed package's registered entrypoints. ``cost=True``
    adds the graft-cost pass against the committed COST_BASELINE.json.
    """
    report = Report()
    if jaxpr:
        from .jaxpr_audit import audit_registered_entrypoints
        report.extend(audit_registered_entrypoints())
    if ast:
        from .ast_lint import lint_tree
        report.extend(lint_tree(root))
    if sentinel:
        from .sentinel import run_sentinel
        report.extend(run_sentinel(root))
    if lattice:
        from .ladders import run_ladders
        from .retrace import run_retrace
        from .warm_check import run_warm_check
        report.extend(run_ladders(root))
        report.extend(run_retrace(root))
        report.extend(run_warm_check(root))
    if cost:
        from .baseline import run_cost_pass
        findings, section = run_cost_pass()
        report.extend(findings)
        report.cost = section
    return report
