"""graft-sentinel rule family 4 — the Pallas DMA protocol.

The graft-tide streaming kernels overlap HBM->VMEM copies with compute
via ``pltpu.make_async_copy(src, dst, sem).start()`` / ``.wait()`` and
ping-pong VMEM buffers. Three protocol properties are checkable from
the kernel AST:

* ``dma-start-no-wait`` / ``dma-wait-no-start`` — every semaphore that
  is started must also be awaited somewhere in the same kernel (and
  vice versa). An un-awaited start races the copy against the compute
  that reads the destination; an un-started wait deadlocks the grid.
  Matching is kernel-wide and keyed by the semaphore expression with
  subscripts stripped (``sem_e.at[s]`` and ``sem_e.at[slot]`` both key
  as ``sem_e.at`` — per-slot pairing happens through helper functions,
  which a lexical checker pools rather than path-splits).
* ``dma-double-buffer`` — two-plus starts into the SAME
  constant-indexed destination slot (``bufs[0]`` twice) means the
  ping-pong alternation was lost: the second copy lands on a buffer the
  compute may still be reading. Alternating patterns index with a
  loop-parity expression (``bufs[li % 2]``) and never trip this.
* ``dma-alias`` — every ``pallas_call`` carrying
  ``input_output_aliases`` must be registered in
  :data:`DMA_ALIAS_SITES`: either as ``"scratch"`` (the aliased input
  is a trace-local accumulator, no donation contract) or as the
  ``(rel, fn)`` of the jit wrapper whose ``donate_argnums`` feeds the
  aliased operands — that wrapper must exist in
  :data:`~.ast_lint.JIT_DECLARATIONS` with a non-empty donate tuple.
  Aliasing a non-donated caller buffer is how "XLA wrote the output
  over an input the caller still holds" bugs are born.

Kernel discovery reuses the pass-2 idiom: a function name passed as the
first argument to ``pl.pallas_call``/``pltpu.pallas_call``. Fixture
trees register alias sites inline via ``GRAFT_SENTINEL["dma_alias"]``
(``{"fn": "scratch"}`` or ``{"fn": ["self", "wrapper"]}`` for a
module-local donating wrapper).
"""
from __future__ import annotations

import ast
import re

from .ast_lint import (JIT_DECLARATIONS, _call_name, _jit_decoration,
                       _dotted)

# (rel path, enclosing function of the pallas_call) -> "scratch" | the
# (rel, fn) JIT_DECLARATIONS key of the donating wrapper the aliased
# operands flow through
DMA_ALIAS_SITES: dict[tuple[str, str], "str | tuple[str, str]"] = {
    # out-accumulator init buffers created inside the trace — aliasing
    # avoids the zero-init branch in the kernel, no caller donation
    ("ops/pallas_segment.py", "_gms_forward"): "scratch",
    ("ops/pallas_segment.py", "_gms_grad_w"): "scratch",
    # the fused/DMA ticks alias the resident mirror through the kernel;
    # the donation contract lives on the gnn_streaming jit wrappers
    ("ops/pallas_segment.py", "_fused_forward"):
        ("rca/gnn_streaming.py", "_gnn_fused_tick"),
    ("ops/pallas_segment.py", "_dma_forward"):
        ("rca/gnn_streaming.py", "_gnn_dma_tick"),
}

_PALLAS_CALL = ("pl.pallas_call", "pallas_call", "pltpu.pallas_call")
_MAKE_COPY = ("pltpu.make_async_copy", "make_async_copy",
              "pl.make_async_copy")
_SUBSCRIPT = re.compile(r"\[[^][]*\]")
_CONST_SLOT = re.compile(r"\[\d+\]")


def _sem_key(expr) -> str:
    """Semaphore expression with subscripts stripped."""
    return _SUBSCRIPT.sub("", ast.unparse(expr))


def _copy_args(call: ast.Call) -> "tuple | None":
    """(dst_expr, sem_expr) if the call is make_async_copy(src, dst, sem)."""
    if _call_name(call) in _MAKE_COPY and len(call.args) >= 3:
        return call.args[1], call.args[2]
    return None


class _KernelScan:
    """Pool every start/wait in one kernel body (nested helpers
    included — tile_start/tile_wait pairing crosses them)."""

    def __init__(self, sf, fn: ast.FunctionDef):
        self.sf, self.fn = sf, fn
        # name -> (dst_expr, sem_expr) for `cp = make_async_copy(...)`
        assigned: dict[str, tuple] = {}
        self.starts: list[tuple] = []   # (line, sem key, dst unparse)
        self.waits: list[tuple] = []    # (line, sem key)
        for node in ast.walk(fn):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)):
                pair = _copy_args(node.value)
                if pair is not None:
                    assigned[node.targets[0].id] = pair
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("start", "wait")):
                continue
            recv = node.func.value
            pair = _copy_args(recv) if isinstance(recv, ast.Call) else \
                assigned.get(recv.id) if isinstance(recv, ast.Name) else None
            if pair is None:
                continue
            dst, sem = pair
            if node.func.attr == "start":
                self.starts.append((node.lineno, _sem_key(sem),
                                    ast.unparse(dst)))
            else:
                self.waits.append((node.lineno, _sem_key(sem)))

    def run(self) -> None:
        started = {k for _l, k, _d in self.starts}
        waited = {k for _l, k in self.waits}
        for line, key, _dst in sorted(self.starts):
            if key not in waited:
                self.sf.hit(
                    "dma-start-no-wait", line,
                    f"async copy started on semaphore '{key}' in kernel "
                    f"'{self.fn.name}' with no matching .wait() anywhere "
                    "in the kernel — the compute races the in-flight "
                    "copy into its destination")
                break   # one finding per kernel keeps the report readable
        for line, key in sorted(self.waits):
            if key not in started:
                self.sf.hit(
                    "dma-wait-no-start", line,
                    f".wait() on semaphore '{key}' in kernel "
                    f"'{self.fn.name}' with no matching .start() — the "
                    "grid deadlocks on a semaphore nothing signals")
                break
        slots: dict[str, int] = {}
        for line, _key, dst in sorted(self.starts):
            if not _CONST_SLOT.search(dst):
                continue            # parity-indexed ping-pong: fine
            if dst in slots:
                self.sf.hit(
                    "dma-double-buffer", line,
                    f"second DMA start into constant slot '{dst}' (first "
                    f"at line {slots[dst]}) in kernel '{self.fn.name}' — "
                    "double-buffering requires alternating slots "
                    "(index by loop parity), or the copy lands on a "
                    "buffer the compute still reads")
            slots.setdefault(dst, line)


def _local_donating_wrappers(sf) -> set[str]:
    out = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.FunctionDef):
            dec = _jit_decoration(node)
            if dec is not None and dec[1]:
                out.add(node.name)
    return out


def _check_alias_sites(sf) -> None:
    inline = sf.inline.get("dma_alias", {})
    # map each pallas_call to its enclosing function by a scoped walk
    def scan(node, fname: str) -> None:
        for child in ast.iter_child_nodes(node):
            nf = child.name if isinstance(child, ast.FunctionDef) else fname
            if isinstance(child, ast.Call) \
                    and _call_name(child) in _PALLAS_CALL \
                    and any(kw.arg == "input_output_aliases"
                            for kw in child.keywords):
                decl = DMA_ALIAS_SITES.get((sf.rel, fname),
                                           inline.get(fname))
                if decl is None:
                    sf.hit(
                        "dma-alias", child.lineno,
                        f"pallas_call with input_output_aliases in "
                        f"'{fname}' is not registered in "
                        "DMA_ALIAS_SITES — declare the aliased operands "
                        "as trace-local scratch or name the donating jit "
                        "wrapper they flow through")
                elif decl != "scratch":
                    wrapper_rel, wrapper_fn = tuple(decl)
                    if wrapper_rel == "self":
                        ok = wrapper_fn in _local_donating_wrappers(sf)
                    else:
                        declared = JIT_DECLARATIONS.get(
                            (wrapper_rel, wrapper_fn))
                        ok = bool(declared and declared[1])
                    if not ok:
                        sf.hit(
                            "dma-alias", child.lineno,
                            f"alias site '{fname}' names wrapper "
                            f"{(wrapper_rel, wrapper_fn)} but that jit "
                            "site has no (non-empty) donate_argnums — "
                            "aliasing a non-donated caller buffer lets "
                            "XLA overwrite an input the caller still "
                            "holds")
            scan(child, nf)
    scan(sf.tree, "<module>")


def check(sf) -> None:
    if not sf.in_hot:
        return
    kernel_names = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call) and _call_name(node) in _PALLAS_CALL:
            if node.args and isinstance(node.args[0], ast.Name):
                kernel_names.add(node.args[0].id)
            elif node.args:
                inner = _dotted(node.args[0])
                if inner:
                    kernel_names.add(inner.rsplit(".", 1)[-1])
    if kernel_names:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.FunctionDef) \
                    and node.name in kernel_names:
                _KernelScan(sf, node).run()
    _check_alias_sites(sf)
