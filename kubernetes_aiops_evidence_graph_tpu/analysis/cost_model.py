"""graft-cost pass — static roofline model over traced entrypoints.

graft-audit (invariants.py) pins *qualitative* hot-path properties; this
module adds the *quantitative* dimension: walk each registered
entrypoint's closed jaxpr and roll up

* **FLOPs** per primitive — exact ``2·b·m·n·k`` for ``dot_general``
  (separately exposed as ``dot_flops`` so tests can pin closed-form
  counts, e.g. gather_matmul_segment = Σ_r 2·rows_r·H²), kernel-sized
  counts for convolutions, one flop per output element for elementwise
  ops, one per input element for reductions and cumulations, one per
  update element for scatters;
* **HBM read/write bytes** from operand/result avals of every leaf
  equation — a traffic *model*, not a fusion-aware simulation: it is
  deterministic, monotone in what the program materializes, and that is
  exactly what a ratchet needs. Pallas kernels are charged at the CALL
  SITE (graft-fuse): a VMEM-resident kernel's true HBM traffic is what
  streams in and out of the ``pallas_call`` — its operand and result
  avals, once per call — while every value flow INSIDE the kernel body
  (ref ``get``/``swap``, tile scratch math) is VMEM traffic and adds
  nothing to HBM bytes. (The previous model charged in-kernel value
  flows as HBM, which both overcharged per-row VMEM accesses ~3× and
  gave fusion zero credit for the inter-kernel HBM round-trips it
  eliminates — the fused tick's whole reason to exist.) graft-tide
  refines the call-site charge for beyond-VMEM kernels: operands and
  results whose block mapping places them in ANY memory space stay
  HBM-resident — the runtime does NOT stream them through VMEM, the
  kernel moves exactly the slices it touches with explicit async
  copies — so ANY-space positions are excluded from the call-site
  bytes and every in-kernel ``dma_start`` is charged its precise
  payload instead (indexer shape × itemsize, read when the HBM side is
  the source, write when it is the destination, loop-weighted like any
  other eqn; ``dma_wait`` moves nothing and costs nothing). Without
  this split a 500k-pod DMA tick would be billed the full resident
  mirror per call — orders of magnitude above the tile traffic it
  actually streams — and the A/B record against
  ``dma_tick_traffic_floor`` could never hold. In-kernel
  materialization stays policed by the per-intermediate byte budget and
  the peak-liveness number below, which DO keep counting kernel values;
* **peak live-intermediate bytes** via per-scope liveness (def →
  last-use) with container equations contributing their inner scope's
  peak while live. Ref avals are excluded here too: a kernel ref is a
  VMEM view of an outer operand that is already alive in the enclosing
  scope, so counting the ref again would double-charge every resident
  buffer;
* **collective census** — dynamic count and payload bytes per collective
  primitive (``ppermute``/``psum``/``all_gather``/…), checked against the
  per-entrypoint :class:`~.comms.CostSpec` by comms.py.

Loop handling: ``scan`` multiplies inner costs by its static ``length``
(``fori_loop`` with Python-int bounds lowers to scan, so the ring halo's
D ppermutes are counted, not just the single traced eqn); ``while``
bodies are counted once (trip count is not static); ``cond`` sums all
branches (a deterministic upper bound); ``pallas_call`` kernel bodies are
multiplied by the static grid size (the traced jaxpr is ONE grid step —
without the weight a tiled kernel would model a single tile's FLOPs, and
the closed-form pins in tests/test_graft_cost.py would not hold). Peak
bytes are never multiplied — iterations reuse the same buffers, and a
Pallas grid revisits the same VMEM blocks.

Everything here is abstract: no FLOP runs, big shapes cost nothing.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from .comms import COLLECTIVE_PRIMS
from .findings import Finding
from .invariants import SCATTER_PRIMS, _aval_bytes, _iter_sub_jaxprs
from .registry import SkipEntrypoint

# one modeled flop per OUTPUT element
ELEMENTWISE_PRIMS = frozenset({
    "add", "sub", "mul", "div", "rem", "max", "min", "pow", "integer_pow",
    "exp", "exp2", "log", "log1p", "expm1", "tanh", "logistic", "erf",
    "erfc", "erf_inv", "rsqrt", "sqrt", "cbrt", "sin", "cos", "tan",
    "atan2", "neg", "abs", "sign", "floor", "ceil", "round", "clamp",
    "select_n", "square", "nextafter", "is_finite",
    "eq", "ne", "ge", "gt", "le", "lt", "and", "or", "xor", "not",
    "shift_left", "shift_right_logical", "shift_right_arithmetic",
})
# one modeled flop per INPUT element
REDUCE_PRIMS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "argmax", "argmin", "cumsum", "cumprod", "cummax",
    "cummin", "cumlogsumexp",
})


@dataclass
class EntryCost:
    """Rolled-up modeled cost of one traced entrypoint."""
    name: str
    flops: int = 0                 # total modeled FLOPs
    dot_flops: int = 0             # dot_general subset (closed-form testable)
    hbm_read_bytes: int = 0
    hbm_write_bytes: int = 0
    peak_intermediate_bytes: int = 0
    collective_bytes: int = 0      # total payload over all collectives
    # collective prim -> {"count", "bytes", "max_op_bytes"} (loop-weighted)
    collectives: dict = field(default_factory=dict)
    eqn_counts: dict = field(default_factory=dict)   # loop-weighted censuses

    @property
    def hbm_bytes(self) -> int:
        return self.hbm_read_bytes + self.hbm_write_bytes

    @property
    def arithmetic_intensity(self) -> float:
        return self.flops / max(self.hbm_bytes, 1)

    def to_dict(self) -> dict:
        return {
            "flops": self.flops,
            "dot_flops": self.dot_flops,
            "hbm_read_bytes": self.hbm_read_bytes,
            "hbm_write_bytes": self.hbm_write_bytes,
            "hbm_bytes": self.hbm_bytes,
            "peak_intermediate_bytes": self.peak_intermediate_bytes,
            "arithmetic_intensity": round(self.arithmetic_intensity, 4),
            "collective_bytes": self.collective_bytes,
            "collectives": self.collectives,
        }


def _numel(aval) -> int:
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _dot_general_flops(eqn) -> int:
    """2*b*m*n*k from the operand shapes and dimension numbers."""
    (lc, rc), (lb, _rb) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    k = 1
    for i in lc:
        k *= int(lhs[i])
    b = 1
    for i in lb:
        b *= int(lhs[i])
    m = 1
    for i, d in enumerate(lhs):
        if i not in lc and i not in lb:
            m *= int(d)
    n = 1
    for i, d in enumerate(rhs):
        if i not in rc and i not in _rb:
            n *= int(d)
    return 2 * b * m * n * k


def _conv_flops(eqn) -> int:
    """2 * output elements * (per-group input channels * kernel spatial)."""
    dn = eqn.params["dimension_numbers"]
    rhs = eqn.invars[1].aval.shape
    out_elems = _numel(eqn.outvars[0].aval)
    spec = dn.rhs_spec            # (out_feat, in_feat, *spatial)
    per_out = int(rhs[spec[1]])
    for i in spec[2:]:
        per_out *= int(rhs[i])
    return 2 * out_elems * per_out


def _eqn_flops(eqn) -> tuple[int, int]:
    """(total_flops, dot_flops) modeled for one leaf equation."""
    prim = eqn.primitive.name
    if prim == "dot_general":
        f = _dot_general_flops(eqn)
        return f, f
    if prim == "conv_general_dilated":
        return _conv_flops(eqn), 0
    if prim in ELEMENTWISE_PRIMS:
        return sum(_numel(v.aval) for v in eqn.outvars), 0
    if prim in REDUCE_PRIMS:
        return sum(_numel(v.aval) for v in eqn.invars
                   if not hasattr(v, "val")), 0
    if prim in SCATTER_PRIMS:
        # one accumulate per update element (invars: operand, indices, updates)
        return _numel(eqn.invars[2].aval), 0
    return 0, 0


def _is_var(v) -> bool:
    return not hasattr(v, "val")      # Literals carry .val


def _is_ref(aval) -> bool:
    """Pallas/state Ref avals (resident buffers, not streamed values)."""
    return hasattr(aval, "inner_aval")


def _space(aval) -> str:
    """Normalized memory-space tag of an aval ('' when unspaced)."""
    return str(getattr(aval, "memory_space", None) or "").lower()


def _pallas_any_positions(eqn) -> tuple[set, set]:
    """(input idxs, output idxs) of ANY-memory-space pallas_call operands.

    ``block_mappings`` lists inputs then outputs; invars additionally
    lead with ``num_index_operands`` scalar-prefetch args that have no
    block mapping (and ARE real call-site transfers, so never skipped).
    """
    gm = eqn.params.get("grid_mapping")
    bms = list(getattr(gm, "block_mappings", ()) or ())
    nidx = int(getattr(gm, "num_index_operands", 0) or 0)
    nin = int(getattr(gm, "num_inputs", len(bms)) or 0)
    ins: set = set()
    outs: set = set()
    for j, bm in enumerate(bms):
        aval = getattr(bm, "transformed_block_aval", None)
        if "any" not in _space(aval):
            continue
        if j < nin:
            ins.add(nidx + j)
        else:
            outs.add(j - nin)
    return ins, outs


def _dma_payload_bytes(ref, transforms) -> int:
    """Bytes one async copy moves on `ref`'s side: the last NDIndexer's
    indexer shape (the ref aval's own shape when untransformed) ×
    itemsize."""
    aval = getattr(ref, "aval", None)
    if aval is None:
        return 0
    shape = tuple(getattr(aval, "shape", ()) or ())
    for t in tuple(transforms or ()):
        get_shape = getattr(t, "get_indexer_shape", None)
        if get_shape is not None:
            shape = tuple(get_shape())
    n = 1
    for d in shape:
        n *= int(d)
    return n * int(getattr(getattr(aval, "dtype", None), "itemsize", 4))


def _dma_start_traffic(eqn) -> tuple[int, int]:
    """(hbm_read, hbm_write) bytes for one in-kernel ``dma_start``.

    The flat invars unflatten via ``params['tree']`` to
    ``(src_ref, src_transforms, dst_ref, dst_transforms, sem, ...)``.
    An ANY-space ref lives in HBM: copying FROM it is an HBM read,
    copying TO it is an HBM write; VMEM↔VMEM copies cost nothing here.
    """
    import jax
    try:
        flat = jax.tree_util.tree_unflatten(
            eqn.params["tree"], list(eqn.invars))
        src, src_tf, dst, dst_tf = flat[0], flat[1], flat[2], flat[3]
    except Exception:  # graft-audit: allow[broad-except] unknown dma layouts must degrade to uncharged, not crash the cost pass
        return 0, 0
    reads = writes = 0
    if "any" in _space(getattr(src, "aval", None)):
        reads = _dma_payload_bytes(src, src_tf)
    if "any" in _space(getattr(dst, "aval", None)):
        writes = _dma_payload_bytes(dst, dst_tf)
    return reads, writes


def _eqn_sub_jaxprs(eqn):
    for pv in eqn.params.values():
        yield from _iter_sub_jaxprs(pv)


def _scope_peak(jaxpr) -> int:
    """Peak live bytes within one jaxpr scope (def → last-use liveness;
    container eqns contribute their inner scope's peak while live)."""
    eqns = jaxpr.eqns
    last_use: dict[int, int] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if _is_var(v):
                last_use[id(v)] = i
    for v in jaxpr.outvars:
        if _is_var(v):
            last_use[id(v)] = len(eqns)
    alive: dict[int, int] = {}
    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        if not _is_ref(v.aval):     # refs alias buffers the OUTER scope owns
            alive[id(v)] = _aval_bytes(v.aval)
    peak = sum(alive.values())
    for i, eqn in enumerate(eqns):
        sub_peak = 0
        for sub in _eqn_sub_jaxprs(eqn):
            sub_peak = max(sub_peak, _scope_peak(sub))
        for v in eqn.outvars:
            if not _is_ref(v.aval):
                alive[id(v)] = _aval_bytes(v.aval)
        peak = max(peak, sum(alive.values()) + sub_peak)
        for v in list(eqn.invars) + list(eqn.outvars):
            if _is_var(v) and last_use.get(id(v), -1) <= i:
                alive.pop(id(v), None)
    return peak


def cost_jaxpr(name: str, closed_jaxpr) -> EntryCost:
    """Walk one traced entrypoint into an :class:`EntryCost`."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    cost = EntryCost(name=name)

    def walk(jx, mult: int, in_kernel: bool = False) -> None:
        for eqn in jx.eqns:
            prim = eqn.primitive.name
            inner_mult = mult
            inner_kernel = in_kernel
            if prim == "scan":
                inner_mult = mult * int(eqn.params.get("length", 1))
            elif prim == "pallas_call":
                # the kernel jaxpr is one grid step: weight COMPUTE by
                # grid size; HBM traffic is charged HERE, at the call
                # site — the kernel's operand/result streams are what
                # actually crosses HBM↔VMEM (once per call: constant-
                # index blocks load once, tiled blocks tile the same
                # total bytes), and everything inside the body is VMEM
                grid = getattr(eqn.params.get("grid_mapping"), "grid",
                               ()) or ()
                steps = 1
                for d in grid:
                    steps *= int(d)
                inner_mult = mult * max(steps, 1)
                inner_kernel = True
                # graft-tide: ANY-space positions are HBM-resident — the
                # kernel's explicit dma_starts (priced below) move their
                # traffic, not the call-site stream
                any_in, any_out = _pallas_any_positions(eqn)
                call_reads = sum(
                    _aval_bytes(v.aval) for i, v in enumerate(eqn.invars)
                    if _is_var(v) and not _is_ref(v.aval)
                    and i not in any_in)
                call_writes = sum(
                    _aval_bytes(v.aval) for k, v in enumerate(eqn.outvars)
                    if not _is_ref(v.aval) and k not in any_out)
                cost.hbm_read_bytes += call_reads * mult
                cost.hbm_write_bytes += call_writes * mult
            subs = list(_eqn_sub_jaxprs(eqn))
            if subs:
                for sub in subs:
                    walk(sub, inner_mult, inner_kernel)
                continue
            cost.eqn_counts[prim] = cost.eqn_counts.get(prim, 0) + mult
            flops, dot = _eqn_flops(eqn)
            cost.flops += flops * mult
            cost.dot_flops += dot * mult
            if in_kernel and prim == "dma_start":
                dma_r, dma_w = _dma_start_traffic(eqn)
                cost.hbm_read_bytes += dma_r * mult
                cost.hbm_write_bytes += dma_w * mult
            if not in_kernel:
                reads = sum(_aval_bytes(v.aval) for v in eqn.invars
                            if _is_var(v) and not _is_ref(v.aval))
                writes = sum(_aval_bytes(v.aval) for v in eqn.outvars
                             if not _is_ref(v.aval))
                cost.hbm_read_bytes += reads * mult
                cost.hbm_write_bytes += writes * mult
            if prim in COLLECTIVE_PRIMS:
                # payload: what moves over the interconnect — the gathered
                # result for all_gather, the shipped operand otherwise
                if prim == "all_gather":
                    payload = writes
                else:
                    payload = reads
                rec = cost.collectives.setdefault(
                    prim, {"count": 0, "bytes": 0, "max_op_bytes": 0})
                rec["count"] += mult
                rec["bytes"] += payload * mult
                rec["max_op_bytes"] = max(rec["max_op_bytes"], payload)
                cost.collective_bytes += payload * mult

    walk(jaxpr, 1)
    cost.peak_intermediate_bytes = _scope_peak(jaxpr)
    return cost


def cost_entrypoint(entry) -> EntryCost:
    """Build + trace + cost one registry entry."""
    import jax
    fn, args = entry.build()
    return cost_jaxpr(entry.name, jax.make_jaxpr(fn)(*args))


def cost_entrypoints(entrypoints):
    """(name -> EntryCost, trace-failure findings, skipped names)."""
    costs: dict[str, EntryCost] = {}
    findings: list[Finding] = []
    skipped: list[str] = []
    for entry in entrypoints:
        try:
            costs[entry.name] = cost_entrypoint(entry)
        except SkipEntrypoint as exc:
            skipped.append(f"{entry.name} (skipped: {exc})")
        except Exception as exc:  # graft-audit: allow[broad-except] any trace failure must surface as a finding, not crash the cost pass
            findings.append(Finding(
                rule="trace-error", where=entry.name,
                message=f"{type(exc).__name__}: {exc}", pass_name="cost"))
    return costs, findings, skipped
