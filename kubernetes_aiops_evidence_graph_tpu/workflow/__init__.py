from .engine import (
    NonRetryableError,
    RetryPolicy,
    Step,
    StepFailed,
    WorkflowEngine,
    WorkflowFenced,
)
from .incident_workflow import (
    IncidentContext,
    incident_steps,
    run_incident_workflow,
)
from .worker import IncidentWorker

__all__ = [
    "WorkflowEngine", "Step", "RetryPolicy", "StepFailed", "NonRetryableError",
    "WorkflowFenced", "IncidentContext", "incident_steps",
    "run_incident_workflow", "IncidentWorker",
]
