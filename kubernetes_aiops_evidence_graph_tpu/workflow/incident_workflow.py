"""The 12-step incident workflow.

Step-for-step parity with the reference IncidentWorkflow
(incident_workflow.py:19-31 docstring, :55-292 body) and its activities
(activities.py:25-363), with the reference's timeout budget:

 1 collect_evidence   5m   collectors (actually parallel) + persist
 2 build_graph        2m   batch ingest into the in-memory store
 3 generate_hypotheses 3m  rca_backend plugin (cpu|tpu) + optional LLM
 4 rank_hypotheses    30s  (constant-folded; recorded for parity)
 5 generate_runbook   30s
 6 calculate_blast_radius 30s
 7 evaluate_policy    30s  proposes the top hypothesis' MACHINE action —
                           never prose (fixes SURVEY.md §3.6 item 6)
 8 request_approval   4h   dev auto-approve; else ApprovalBroker (real
                           response path, unlike the reference's stub)
 9 execute_remediation 5m
10 verify_remediation 2m wait + 2m verify
11 create_ticket      30s  iff not allowed or verification failed
12 close_incident     30s  resolved/closed by verification outcome
"""
from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Any

from ..collectors import collect_all, default_collectors
from ..config import Settings, get_settings
from ..graph import GraphBuilder, build_snapshot
from ..integrations import JiraClient, SlackClient
from ..models import (
    ActionStatus,
    ApprovalRequest,
    Hypothesis,
    HypothesisCategory,
    HypothesisSource,
    Incident,
    IncidentStatus,
    RemediationAction,
)
from ..observability import (
    HYPOTHESES_GENERATED,
    INCIDENTS_RESOLVED,
    RCA_DURATION,
    REMEDIATION_ATTEMPTS,
    get_logger,
)
from ..rca import get_backend
from ..rca.llm import LLMSummarizer
from ..remediation import (
    RemediationCompensator,
    RemediationExecutor,
    RemediationOrchestrator,
    RemediationVerifier,
)
from ..runbook import RunbookGenerator
from ..storage import Database
from ..utils.timeutils import utcnow
from .engine import Step, StepFailed, WorkflowEngine, WorkflowFenced

log = get_logger("incident_workflow")


@dataclass
class IncidentContext:
    """Everything a workflow run needs; results accumulate per step."""
    incident: Incident
    cluster: Any                       # ClusterBackend (+ admin surface)
    db: Database
    builder: GraphBuilder
    settings: Settings = field(default_factory=get_settings)
    results: dict[str, Any] = field(default_factory=dict)
    # transient (not journal-serialized; rehydrated from DB on replay)
    evidence_dicts: list[dict] = field(default_factory=list)
    hypotheses: list[Hypothesis] = field(default_factory=list)
    scorer: Any = None                 # resident StreamingScorer (serving path)
    tenant: str = "default"            # graft-surge: this incident's tenant
    #                                    (names its region on a multi-tenant
    #                                    pack; SLO samples carry the label)
    action: RemediationAction | None = None
    baseline: dict = field(default_factory=dict)
    slack: SlackClient | None = None
    jira: JiraClient | None = None
    dedup: Any = None  # AlertDeduplicator; fingerprint released on close
    # graft-saga chaos seam: a rca/faults.FaultInjector whose lifecycle
    # hooks (collect | journal_put | wf_execute | verify | compensate |
    # crash_restart) fire at the stage boundaries below
    faults: Any = None


def _fault(ctx: IncidentContext, stage: str) -> None:
    if ctx.faults is not None:
        ctx.faults.at(stage)


def _ensure_hypotheses(ctx: IncidentContext) -> list[Hypothesis]:
    """Rehydrate hypotheses from storage after a journal replay skipped
    generate_hypotheses (resume-after-crash durability)."""
    if ctx.hypotheses:
        return ctx.hypotheses
    rows = ctx.db.hypotheses_for(ctx.incident.id)
    ctx.hypotheses = [
        Hypothesis(
            id=r["id"], incident_id=r["incident_id"],
            category=HypothesisCategory(r["category"]), title=r["title"],
            description=r["description"] or "", confidence=r["confidence"],
            rank=r["rank"], final_score=r["final_score"], rule_id=r["rule_id"],
            backend=r["backend"],
            recommended_actions=r["recommended_actions"],
            generated_by=HypothesisSource(r["generated_by"]),
        ) for r in rows
    ]
    return ctx.hypotheses


def _ensure_action(ctx: IncidentContext) -> RemediationAction | None:
    """Rehydrate the proposed action from storage after replay."""
    if ctx.action is not None:
        return ctx.action
    rows = ctx.db.actions_for(ctx.incident.id)
    if not rows:
        return None
    import json as _json
    # compensation rows ride the same table under suffixed idempotency
    # keys — the WORKFLOW action is the newest non-derived row
    primary = [r for r in rows
               if ":" not in (r["idempotency_key"] or "")] or rows
    r = primary[-1]
    ctx.action = RemediationAction(
        id=r["id"], incident_id=r["incident_id"],
        hypothesis_id=r["hypothesis_id"],
        idempotency_key=r["idempotency_key"],
        action_type=r["action_type"], target_resource=r["target_resource"],
        target_namespace=r["target_namespace"],
        # parameters + execution_result were dropped by the pre-saga
        # rehydration — compensation needs prev_replicas and the executor
        # needs the requested replica target, so a replayed context must
        # carry both
        parameters=_json.loads(r["parameters"] or "{}"),
        risk_level=r["risk_level"],
        blast_radius_score=r["blast_radius_score"],
        environment=r["environment"], status=ActionStatus(r["status"]),
        status_reason=r["status_reason"],
        requires_approval=bool(r["requires_approval"]),
        approved_by=r["approved_by"],
        execution_result=(_json.loads(r["execution_result"])
                          if r["execution_result"] else None),
        error_message=r["error_message"],
    )
    return ctx.action


# -- step implementations (activities.py analogs) --------------------------

def collect_evidence(ctx: IncidentContext) -> dict:
    _fault(ctx, "collect")
    collectors = default_collectors(ctx.cluster, ctx.settings)
    results = collect_all(ctx.incident, collectors, parallel=True)
    all_ev = [e for r in results for e in r.evidence]
    ctx.db.insert_evidence(all_ev)  # one batch, not per-row (activities.py:61-84)
    ctx.evidence_dicts = [e.model_dump(mode="json") for e in all_ev]
    ctx.results["_collector_results"] = results  # for build_graph (in-memory)
    return {
        "evidence_count": len(all_ev),
        "collectors": {r.collector_name: r.success for r in results},
        "errors": [err for r in results for err in r.errors],
        # graft-saga replay fidelity: the collector-emitted graph payload
        # (topology entities/relations) existed only in memory, so a
        # crash after this step rebuilt a THINNER graph than the original
        # run saw — journal it with the step so build_graph's replay
        # re-ingests the exact same graph (bounded: collectors only emit
        # this incident's service/namespace neighborhood)
        "graph": {
            "entities": [e.model_dump(mode="json")
                         for r in results for e in r.entities],
            "relations": [rel.model_dump(mode="json")
                          for r in results for rel in r.relations],
        },
    }


def build_graph(ctx: IncidentContext) -> dict:
    results = ctx.results.pop("_collector_results", None)
    if results is None:  # replayed run: rebuild from persisted evidence
        from ..models import (
            CollectorResult, Evidence, GraphEntity, GraphRelation)
        evs = [Evidence(**{**row, "data": row["data"]})
               for row in _evidence_rows(ctx)]
        graph = (ctx.results.get("collect_evidence") or {}).get("graph") or {}
        results = [CollectorResult(
            collector_name="replay", evidence=evs,
            entities=[GraphEntity(**d) for d in graph.get("entities", [])],
            relations=[GraphRelation(**d)
                       for d in graph.get("relations", [])])]
    stats = ctx.builder.ingest(ctx.incident, results)
    out = {k: v for k, v in stats.items() if k != "incident_node"}
    # graft-surge: feed the webhook's delta batch into the resident
    # scorer's bounded tick_async queue RIGHT HERE — the device executes
    # (or coalesces, under burst) while the workflow's host steps
    # continue, and generate_hypotheses later pays only a deferred
    # newest-tick fetch instead of a synchronous dispatch+fetch
    # round-trip. absorb() is non-blocking (journal drain + jit enqueue);
    # this step already runs on an executor thread.
    if ctx.scorer is not None and hasattr(ctx.scorer, "absorb"):
        try:
            tick = ctx.scorer.absorb()
            out["absorbed"] = bool(tick.get("dispatched")
                                   or tick.get("coalesced"))
        except Exception as exc:  # graft-audit: allow[broad-except] advisory pre-tick: the verdict boundary re-syncs, and a poisoned absorb must not fail graph ingest
            log.warning("absorb_failed", incident=str(ctx.incident.id),
                        error=str(exc))
            out["absorbed"] = False
    return out


def _evidence_rows(ctx: IncidentContext) -> list[dict]:
    rows = ctx.db.evidence_for(ctx.incident.id)
    for r in rows:
        r.setdefault("incident_id", str(ctx.incident.id))
    return rows


def _ensure_evidence(ctx: IncidentContext) -> list[dict]:
    """Rehydrate evidence dicts from storage after a journal replay (the
    transient ctx.evidence_dicts dies with the crashed worker; runbooks
    and tickets generated on the resumed run must see the same evidence
    the original run saw)."""
    if not ctx.evidence_dicts:
        ctx.evidence_dicts = _evidence_rows(ctx)
    return ctx.evidence_dicts


def _streaming_hypotheses(ctx: IncidentContext,
                          backend_name: str) -> list[Hypothesis] | None:
    """Score via the resident scorer: journal sync + fused tick — no
    per-incident snapshot rebuild (VERDICT r2 item 2; replaces the
    reference's per-incident collect→Cypher→score, activities.py:26-164).
    One protocol for both resident backends — rules (StreamingScorer) and
    learned (GnnStreamingScorer, VERDICT r4 ask 2): serve() coalesces
    concurrent callers onto shared ticks, the batched raw dict contains
    every live incident's row, and only the row-slice keys differ per
    backend. None = incident not in the graph, caller falls back to the
    snapshot path.

    graft-surge: ``serve(newest=True)`` makes this the ASYNC verdict
    boundary — build_graph already absorbed the webhook deltas into the
    pipelined tick queue, so in steady state the generation fetches the
    newest in-flight tick's result (one readback, zero fresh dispatches)
    instead of a synchronous per-incident rescore round-trip. On a
    multi-tenant pack (rca/surge.MultiTenantScorer) the same call serves
    EVERY tenant's concurrent incidents from one device pass; this
    incident's row is addressed by its tenant-namespaced slot id and
    sliced back to the local id for results()."""
    nid = f"incident:{ctx.incident.id}"
    sid = ctx.scorer.serving_node_id(nid, tenant=ctx.tenant)
    raw = ctx.scorer.serve(newest=True)
    try:
        i = raw["incident_ids"].index(sid)
    except ValueError:
        return None
    # key off the RESULT surface, not the configured backend: a
    # checkpoint-unusable worker serves rca_backend=gnn from the rules
    # tier (worker._build_gnn_scorer), whose raw dict carries
    # matched/scores instead of probs — slicing must follow the verdict
    # that was actually produced
    if backend_name == "gnn" and "probs" in raw:
        one = {"incident_ids": [nid], "probs": raw["probs"][i:i + 1]}
        return get_backend("gnn").results(None, raw=one)[0].hypotheses
    one = {  # slice this incident's row; results() is row-wise
        "incident_ids": [nid],
        "matched": raw["matched"][i:i + 1],
        "scores": raw["scores"][i:i + 1],
        "any_match": raw["any_match"][i:i + 1],
    }
    return get_backend("tpu").results(raw=one)[0].hypotheses


def generate_hypotheses(ctx: IncidentContext) -> dict:
    import time as _t
    t0 = _t.perf_counter()
    backend_name = ctx.settings.rca_backend
    mode = backend_name
    hyps = None
    if backend_name in ("tpu", "gnn") and ctx.scorer is not None:
        hyps = _streaming_hypotheses(ctx, backend_name)
        if hyps is not None:
            mode = "streaming"
    if hyps is None:
        if backend_name in ("tpu", "gnn"):   # snapshot-scoring backends
            snapshot = build_snapshot(ctx.builder.store, ctx.settings)
            backend = get_backend(backend_name)
            if backend_name == "tpu":
                # graft-fleet satellite (ROADMAP item 2 slice): the
                # narrowed verdict fetch is the DEFAULT — the wide
                # conditions/matched/scores tables never leave the device
                # unless settings.workflow_verdict_fields asks for them
                # ("full"/"all" restores every-matched-rule hypotheses)
                mode = getattr(ctx.settings, "workflow_verdict_fields",
                               "top")
                fields = "full" if mode in ("full", "all") else "top"
                all_results = backend.results(
                    raw=backend.score_snapshot(snapshot, fields=fields))
            else:
                all_results = backend.results(snapshot)
            mine = [r for r in all_results
                    if str(r.incident_id) == str(ctx.incident.id)]
            hyps = mine[0].hypotheses if mine else []
        else:
            hyps = get_backend("cpu").score_incident(
                ctx.incident.id, ctx.evidence_dicts or _evidence_rows(ctx)).hypotheses
    llm = LLMSummarizer(ctx.settings)
    if llm.enabled:
        hyps = llm.enhance_hypotheses(ctx.incident, hyps, ctx.evidence_dicts)
    ctx.hypotheses = hyps
    # graft-scope SLO boundary: the hypotheses ARE the verdict — close
    # the webhook→verdict latency sample this incident opened at the
    # ingestion edge (no-op for incidents that never passed a webhook)
    from ..observability.scope import SCOPE
    SCOPE.verdict_served(
        str(ctx.incident.id), backend=backend_name,
        shards=int(getattr(ctx.settings, "serve_graph_shards", 1)))
    RCA_DURATION.observe(_t.perf_counter() - t0, backend=backend_name)
    for h in hyps:
        HYPOTHESES_GENERATED.inc(category=getattr(h.category, "value", str(h.category)))
    ctx.db.insert_hypotheses(hyps)
    return {
        "count": len(hyps),
        "backend": backend_name,
        "mode": mode,
        "top_rule": hyps[0].rule_id if hyps else None,
        "top_confidence": hyps[0].confidence if hyps else None,
    }


def rank_hypotheses(ctx: IncidentContext) -> dict:
    # ranking is constant-folded into generation (ruleset.py); recorded for
    # lifecycle parity with activities.py:164-173. _ensure_hypotheses, not
    # ctx.hypotheses: a resume whose crash ate only this step's commit
    # must re-rank the PERSISTED hypotheses, not an empty transient list.
    hyps = _ensure_hypotheses(ctx)
    return {"ranked": [h.rule_id for h in hyps],
            "top_score": hyps[0].final_score if hyps else None}


def generate_runbook(ctx: IncidentContext) -> dict:
    if not _ensure_hypotheses(ctx):
        return {"generated": False}
    rb = RunbookGenerator().generate(ctx.incident, ctx.hypotheses[0],
                                     evidence=_ensure_evidence(ctx))
    ctx.db.insert_runbook(rb)
    return {"generated": True, "title": rb.title, "steps": len(rb.steps)}


def calculate_blast_radius(ctx: IncidentContext) -> dict:
    orch = RemediationOrchestrator(ctx.cluster, ctx.settings)
    blast = orch.calculate_blast_radius(ctx.incident)
    return blast.model_dump(mode="json")


def evaluate_policy(ctx: IncidentContext) -> dict:
    """Propose the top hypothesis' machine action (activities.py:207-246 —
    but using the structured ``action`` field, not recommended_actions[0]
    prose)."""
    hyps = _ensure_hypotheses(ctx)
    top = hyps[0] if hyps else None
    machine_action = _machine_action(top)
    if machine_action is None:
        return {"proposed": False, "reason": "no machine-executable action"}
    orch = RemediationOrchestrator(ctx.cluster, ctx.settings)
    target = (ctx.incident.service or ctx.incident.namespace)
    if machine_action == "cordon_node":
        pods = ctx.cluster.list_pods(ctx.incident.namespace, ctx.incident.service)
        target = pods[0].node if pods else target
    action = orch.propose_action(ctx.incident, machine_action, target)
    action.hypothesis_id = top.id if top else None
    ctx.action = action
    ctx.db.upsert_action(action)
    return {
        "proposed": True,
        "action_type": action.action_type.value,
        "target": action.target_resource,
        "allowed": action.status == ActionStatus.PROPOSED,
        "requires_approval": action.requires_approval,
        "reason": action.status_reason,
    }


def _machine_action(top: Hypothesis | None) -> str | None:
    if top is None:
        return None
    from ..rca import RULE_INDEX, RULES
    if top.rule_id in RULE_INDEX:
        rule = RULES[RULE_INDEX[top.rule_id]]
        return rule.action.value if rule.action else None
    return None


def request_approval(ctx: IncidentContext) -> dict:
    action = _ensure_action(ctx)
    assert action is not None
    if not action.requires_approval:
        action.status = ActionStatus.APPROVED
        action.approved_by = "auto-dev"  # activities.py:251-252
        ctx.db.upsert_action(action)
        return {"approved": True, "by": "auto-dev"}
    slack = ctx.slack or SlackClient(ctx.settings)
    # graft-saga satellite: rehydrate via _ensure_hypotheses — a
    # resume-after-crash context has empty ctx.hypotheses, and the
    # approver was being asked to sign off on a blank summary
    hyps = _ensure_hypotheses(ctx)
    req = ApprovalRequest(
        action_id=action.id, incident_id=ctx.incident.id,
        incident_title=ctx.incident.title, action_type=action.action_type,
        target_resource=action.target_resource,
        target_namespace=action.target_namespace,
        risk_level=action.risk_level,
        blast_radius_score=action.blast_radius_score,
        hypothesis_summary=hyps[0].description if hyps else "",
    )
    timeout = ctx.settings.approval_timeout_seconds
    resp = slack.request_approval(req, timeout_s=timeout)
    approved = bool(resp and resp.approved)
    action.status = ActionStatus.APPROVED if approved else ActionStatus.REJECTED
    if approved:
        action.approved_by = resp.responder
    else:
        action.rejection_reason = "timeout" if resp is None else (resp.notes or "rejected")
    ctx.db.upsert_action(action)
    return {"approved": approved,
            "by": resp.responder if resp else None,
            "timed_out": resp is None}


def execute_remediation(ctx: IncidentContext) -> dict:
    """graft-saga two-phase execution: the executor journals an intent
    row (idempotency key + pre-action probe + verification baseline)
    into the durable ``action_executions`` ledger BEFORE the cluster
    mutation and a result row after. A crash anywhere in between leaves
    an in-doubt intent that the resumed run RECONCILES against observed
    cluster state — the mutation fires exactly once, never twice. The
    baseline rides the intent row, so a resumed run compares against the
    true pre-action snapshot instead of re-probing the mutated cluster."""
    action = _ensure_action(ctx)
    assert action is not None
    verifier = RemediationVerifier(ctx.cluster)
    executor = RemediationExecutor(
        ctx.cluster, ctx.settings, db=ctx.db,
        fault_hook=(ctx.faults.at if ctx.faults is not None else None))
    baseline = executor.ledger_baseline(action)
    if baseline is None:
        baseline = verifier.capture_baseline(ctx.incident)
    ctx.baseline = baseline
    REMEDIATION_ATTEMPTS.inc(action_type=action.action_type.value)
    executed = executor.execute(action, baseline=baseline)
    ctx.db.upsert_action(executed)
    return {"status": executed.status.value,
            "result": executed.execution_result,
            "error": executed.error_message,
            "baseline": baseline}  # journaled: survives resume


async def verify_remediation(ctx: IncidentContext) -> dict:
    action = _ensure_action(ctx)
    if action is None:
        # graft-saga satellite: a replay whose actions table lost its row
        # (foreign journal, manual surgery) used to crash the verifier —
        # journal a SKIPPED verification instead: success=None is neither
        # the ticket trigger (False) nor a resolved close (True)
        log.warning("verify_skipped_no_action",
                    incident=str(ctx.incident.id))
        return {"success": None, "skipped": "no persisted action"}
    _fault(ctx, "verify")
    await asyncio.sleep(min(ctx.settings.verification_wait_seconds, 120))
    verifier = RemediationVerifier(ctx.cluster)
    baseline = ctx.baseline or (
        ctx.results.get("execute_remediation") or {}).get("baseline") or {}
    result = verifier.verify(ctx.incident, action, baseline)
    ctx.db.insert_verification(result)
    return {"success": result.success,
            "metrics_improved": result.metrics_improved,
            "pods_healthy_after": result.pods_healthy_after}


def compensate_remediation(ctx: IncidentContext) -> dict:
    """graft-saga compensation: verification FAILED on an executed
    action — roll its cluster effect back (scale → restore the
    pre-action replica count, cordon → uncordon, rollback →
    re-rollback; restart-class self-heals), policy-gated and journaled,
    with bounded attempts and escalate-to-human on exhaustion. Runs
    through the same two-phase ledger, so a crash mid-compensation
    reconciles on resume instead of double-firing."""
    action = _ensure_action(ctx)
    if action is None:
        return {"compensated": False, "skipped": "no persisted action"}
    _fault(ctx, "compensate")
    comp = RemediationCompensator(
        ctx.cluster, ctx.settings, db=ctx.db,
        fault_hook=(ctx.faults.at if ctx.faults is not None else None))
    return comp.compensate(action)


def create_ticket(ctx: IncidentContext) -> dict:
    jira = ctx.jira or JiraClient(ctx.settings)
    hyps = _ensure_hypotheses(ctx)
    return jira.create_incident_ticket(ctx.incident, hyps[0] if hyps else None,
                                       evidence=_ensure_evidence(ctx))


def close_incident(ctx: IncidentContext) -> dict:
    verified = (ctx.results.get("verify_remediation") or {}).get("success")
    status = IncidentStatus.RESOLVED if verified else IncidentStatus.CLOSED
    ctx.db.update_incident_status(ctx.incident.id, status, resolved_at=utcnow())
    INCIDENTS_RESOLVED.inc(status=status.value)
    if ctx.dedup is not None:  # allow re-alerting for recurring faults
        ctx.dedup.release(ctx.incident.fingerprint)
    return {"status": status.value}


# -- pipeline assembly ------------------------------------------------------

def _action_allowed(ctx: IncidentContext) -> bool:
    # journal-derived so it survives replay (ctx.action rehydrates lazily)
    policy = ctx.results.get("evaluate_policy") or {}
    return bool(policy.get("proposed") and policy.get("allowed"))


def _approved(ctx: IncidentContext) -> bool:
    return (_action_allowed(ctx)
            and bool((ctx.results.get("request_approval") or {}).get("approved")))


def _needs_ticket(ctx: IncidentContext) -> bool:
    policy = ctx.results.get("evaluate_policy") or {}
    verify = ctx.results.get("verify_remediation") or {}
    return (not policy.get("allowed", False)
            or not (ctx.results.get("request_approval") or {}).get("approved", False)
            or verify.get("success") is False)  # incident_workflow.py:246-250


def _compensation_due(ctx: IncidentContext) -> bool:
    """Saga trigger: the action EXECUTED but verification said the
    cluster did not get better — undo the mutation before ticketing."""
    if not getattr(ctx.settings, "remediation_compensation", False):
        return False
    execute = ctx.results.get("execute_remediation") or {}
    verify = ctx.results.get("verify_remediation") or {}
    return (execute.get("status") == "completed"
            and verify.get("success") is False)


# canonical step order for inspection surfaces (the 13-step lifecycle:
# the reference's 12 + the graft-saga compensation step); kept in sync
# with incident_steps() below
STEP_NAMES = (
    "collect_evidence", "build_graph", "generate_hypotheses",
    "rank_hypotheses", "generate_runbook", "calculate_blast_radius",
    "evaluate_policy", "request_approval", "execute_remediation",
    "verify_remediation", "compensate_remediation", "create_ticket",
    "close_incident",
)


def incident_steps(settings: Settings | None = None) -> list[Step]:
    s = settings or get_settings()
    remediation_on = s.remediation_enabled
    return [
        Step("collect_evidence", collect_evidence, timeout_s=300),
        Step("build_graph", build_graph, timeout_s=120),
        Step("generate_hypotheses", generate_hypotheses, timeout_s=180),
        Step("rank_hypotheses", rank_hypotheses, timeout_s=30),
        Step("generate_runbook", generate_runbook, timeout_s=30),
        Step("calculate_blast_radius", calculate_blast_radius, timeout_s=30),
        Step("evaluate_policy", evaluate_policy, timeout_s=30,
             condition=lambda ctx: remediation_on),
        Step("request_approval", request_approval,
             timeout_s=s.approval_timeout_seconds + 5,
             condition=_action_allowed),
        Step("execute_remediation", execute_remediation, timeout_s=300,
             condition=_approved),
        Step("verify_remediation", verify_remediation,
             timeout_s=s.verification_wait_seconds + 120,
             condition=lambda ctx: (ctx.results.get("execute_remediation") or {}
                                    ).get("status") == "completed"),
        Step("compensate_remediation", compensate_remediation, timeout_s=300,
             condition=_compensation_due),
        Step("create_ticket", create_ticket, timeout_s=30,
             condition=_needs_ticket),
        Step("close_incident", close_incident, timeout_s=30),
    ]


async def run_incident_workflow(
    incident: Incident,
    cluster: Any,
    db: Database,
    builder: GraphBuilder | None = None,
    settings: Settings | None = None,
    engine: WorkflowEngine | None = None,
    slack: SlackClient | None = None,
    jira: JiraClient | None = None,
    dedup: Any = None,
    scorer: Any = None,
    tenant: str = "default",
    faults: Any = None,
) -> dict:
    """Entry point: the reference's `start_workflow("IncidentWorkflow",
    id=f"incident-{id}")` (main.py:406-413).

    graft-saga: the run claims a fenced lease on the workflow id before
    touching the incident. A held lease means another worker is live on
    this workflow — return without driving it. A crash (worker death)
    leaves the lease to EXPIRE, at which point the resumer sweep
    (worker.resume_orphans) reclaims it and re-enters here through the
    journal-replay path; the fencing token keeps a paused-then-woken
    zombie from double-driving the journal."""
    s = settings or get_settings()
    ctx = IncidentContext(
        incident=incident, cluster=cluster, db=db,
        builder=builder or GraphBuilder(), settings=s,
        slack=slack, jira=jira, dedup=dedup, scorer=scorer,
        tenant=tenant, faults=faults,
    )
    engine = engine or WorkflowEngine(db)
    wf_id = f"incident-{incident.id}"
    lease = None
    ttl = float(getattr(s, "workflow_lease_ttl_s", 60.0))
    if getattr(s, "workflow_lease_enabled", False):
        import os
        from uuid import uuid4 as _uuid4
        owner = f"{os.getpid()}:{_uuid4().hex[:8]}"
        token = db.lease_acquire(wf_id, owner, ttl)
        if token is None:
            log.info("workflow_lease_held", workflow=wf_id)
            return {"lease_held": True}
        lease = (owner, token)
        if token > 1:
            _fault(ctx, "crash_restart")  # chaos: die again right away
    db.update_incident_status(incident.id, IncidentStatus.INVESTIGATING)
    released_ok = False
    try:
        results = await engine.run(wf_id, incident_steps(s), ctx,
                                   lease=lease, lease_ttl_s=ttl)
        released_ok = True
        return results
    except WorkflowFenced:
        # benign: the lease expired mid-run and another worker owns the
        # workflow now — do NOT audit a failure, do NOT release (the
        # owner+token match makes a late release a no-op anyway)
        log.warning("workflow_fenced_out", workflow=wf_id)
        return {"lease_fenced": True}
    except StepFailed as exc:
        log.error("workflow_failed", incident=str(incident.id), error=str(exc))
        db.audit(str(incident.id), "workflow_failed", {"error": str(exc)})
        # graft-saga satellite: a StepFailed leaves the incident open with
        # only an audit row — stamp the stalled gauge so the resumer sweep
        # and GET /api/v1/workflows surface it instead of it vanishing
        # into INVESTIGATING forever
        released_ok = True
        from ..observability import metrics as obs_metrics
        obs_metrics.WORKFLOW_STALLED.set(float(len(db.stalled_workflows(
            max_resumes=int(getattr(s, "workflow_max_resumes", 5))))))
        raise
    except Exception as exc:
        log.error("workflow_failed", incident=str(incident.id), error=str(exc))
        db.audit(str(incident.id), "workflow_failed", {"error": str(exc)})
        released_ok = True
        raise
    finally:
        # a CRASH (BaseException, e.g. rca/faults.WorkflowCrash) skips the
        # release on purpose — a dead worker cannot release, the lease
        # must EXPIRE into the resumer's hands
        if lease is not None and released_ok:
            db.lease_release(wf_id, *lease)
