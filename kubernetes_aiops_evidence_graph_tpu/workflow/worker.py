"""Incident worker — the Temporal worker analog.

The reference worker registers the workflow + activities on task queue
"incident-workflow" and scales horizontally (worker.py:31-73). Here: an
asyncio queue with N concurrent workflow slots in one process; horizontal
scale-out is running more processes against the same SQLite/cluster
backends. That is a tested claim, not an aspiration: the step journal is
WAL-mode with busy-timeout writes (storage/sqlite.py _connect) and every
journal write is an idempotent upsert, so tests/test_multiprocess.py
proves two real OS processes can contend on one journal and that a
SIGKILL mid-workflow replays to completion in a second process without
re-executing completed steps.
"""
from __future__ import annotations

import asyncio
import threading
from sqlite3 import Error as sqlite3Error
from typing import Any

from ..config import Settings, get_settings
from ..graph import GraphBuilder
from ..models import Incident
from ..observability import get_logger
from ..observability import metrics as obs_metrics
from ..storage import Database
from .engine import WorkflowEngine
from .incident_workflow import run_incident_workflow

log = get_logger("worker")


def _incident_from_row(row: dict) -> Incident:
    """Rehydrate an Incident from its durable incidents row (the resumer
    re-enters run_incident_workflow with it; pydantic coerces the ISO
    strings and enum values)."""
    return Incident(
        id=row["id"], fingerprint=row["fingerprint"], title=row["title"],
        description=row["description"], severity=row["severity"],
        status=row["status"], source=row["source"], cluster=row["cluster"],
        namespace=row["namespace"], service=row["service"],
        labels=row.get("labels") or {},
        annotations=row.get("annotations") or {},
        started_at=row["started_at"], created_at=row["created_at"],
        updated_at=row["updated_at"],
    )


class IncidentWorker:
    def __init__(
        self,
        cluster: Any,
        db: Database,
        builder: GraphBuilder | None = None,
        settings: Settings | None = None,
        concurrency: int = 4,
        dedup: Any = None,
        surge: Any = None,
        tenant: str = "default",
    ) -> None:
        self.cluster = cluster
        self.db = db
        self.builder = builder or GraphBuilder()
        self.settings = settings or get_settings()
        self.dedup = dedup
        self.concurrency = concurrency
        self.queue: asyncio.Queue[Incident | None] = asyncio.Queue()
        self.engine = WorkflowEngine(db)
        self._tasks: list[asyncio.Task] = []
        self.completed: int = 0
        self.failed: int = 0
        # resident serving scorer (tpu backend): created once, mirrors the
        # store via its change journal — no per-incident snapshot rebuild
        self.scorer: Any = None
        self._scorer_lock = threading.Lock()
        self._warm_thread: threading.Thread | None = None
        # graft-surge: attach this worker's store to a shared multi-tenant
        # SurgeServer (rca/surge.py) — N per-tenant workers then serve off
        # ONE resident pack and their concurrent incidents score in one
        # device pass. Registration is cheap; the pack builds lazily at
        # first serve. ``tenant`` labels this worker's region/SLO samples.
        if surge is not None and self.settings.rca_backend != "tpu":
            # the pack batches the rules scorer's verdict pass; other
            # backends keep their per-tenant resident scorer
            log.warning("surge_requires_tpu_backend",
                        rca_backend=self.settings.rca_backend)
            surge = None
        self.surge = surge
        self.tenant = tenant
        if surge is not None:
            surge.register(tenant, self.builder.store)
        # once the scorer question is settled (a resident scorer exists,
        # or the backend has none), steady-state incidents skip the
        # executor hop entirely — `scorer_resolutions` counts the slow
        # path so tests can pin the fast path actually engages
        self._scorer_resolved = False
        self.scorer_resolutions = 0
        # graft-saga resumer: the periodic sweep task reclaiming expired
        # leases (started by start() when workflow_resume_interval_s > 0)
        self._resume_task: asyncio.Task | None = None
        self.resumed: int = 0

    def serving_scorer(self) -> Any:
        """Lazily build the shared resident scorer: StreamingScorer for
        rca_backend=tpu, GnnStreamingScorer for rca_backend=gnn (the
        learned backend serves under churn too — VERDICT r4 ask 2)."""
        if self.settings.rca_backend not in ("tpu", "gnn"):
            return None
        if self.surge is not None and self.settings.rca_backend == "tpu":
            # graft-surge: the shared multi-tenant pack IS this worker's
            # resident scorer. scorer() (re)builds under the server's own
            # lock when tenants registered since the last build; the
            # shield wrap is a single-store layer and stays off the pack
            # (each tenant's quarantine/heal ladder covers poison, and
            # the pack rebuilds store-derived — logged, never silent).
            if self.settings.shield_enabled:
                log.warning("surge_shield_unsupported", tenant=self.tenant)
            scorer = self.surge.scorer(self.tenant)
            with self._scorer_lock:
                if not getattr(scorer, "_surge_warm_started", False):
                    scorer._surge_warm_started = True
                    scorer.auto_warm_growth = True
                    self._warm_thread = threading.Thread(
                        target=scorer.warm_serving,
                        name="kaeg-warm-serving", daemon=False)
                    self._warm_thread.start()
                self.scorer = scorer
            return scorer
        with self._scorer_lock:
            if self.scorer is None:
                if self.settings.rca_backend == "gnn":
                    scorer = self._build_gnn_scorer()
                else:
                    from ..rca.streaming import StreamingScorer
                    scorer = StreamingScorer(self.builder.store,
                                             self.settings,
                                             mesh=self._serving_mesh())
                # pre-compile the steady-state delta buckets AND the next
                # bucket shapes off the serving path so neither hot ticks
                # nor growth rebuilds pay an XLA compile mid-serve;
                # auto_warm_growth re-arms after every shape change so the
                # guarantee holds for successive growths too
                scorer.auto_warm_growth = True
                self._warm_thread = threading.Thread(
                    target=scorer.warm_serving,
                    name="kaeg-warm-serving", daemon=False)
                self._warm_thread.start()
                if self.settings.shield_enabled:
                    # graft-shield: wrap the resident scorer in the
                    # crash-consistent recovery layer, and on acquisition
                    # either restore a compatible on-disk snapshot+journal
                    # (a prior shield of THIS store lineage — e.g. a
                    # restarted serve loop in the same process) or anchor a
                    # fresh snapshot so recovery is possible from tick one
                    from ..rca.shield import ShieldedScorer
                    scorer = ShieldedScorer(scorer, self.settings)
                    scorer.recover_or_snapshot()
                self.scorer = scorer
            return self.scorer

    def _build_gnn_scorer(self):
        """GnnStreamingScorer, or the RULES serving tier when the
        checkpoint is unusable (corrupt, legacy pre-relation-aware, or
        missing): graft-evolve hot swap multiplies how often checkpoints
        load, and a bad one must degrade serving — verdicts keep flowing
        from the rules fold — never crash the worker. The fallback is
        loud (error log + shield tier counter) and the workflow's
        hypothesis slicing keys off the RESULT surface, so a rules-tier
        scorer under rca_backend=gnn serves rules hypotheses."""
        from ..observability import metrics as obs_metrics
        from ..rca.gnn_backend import CheckpointError
        from ..rca.gnn_streaming import GnnStreamingScorer
        try:
            return GnnStreamingScorer(self.builder.store, self.settings,
                                      mesh=self._serving_mesh())
        except CheckpointError as exc:
            log.error("gnn_checkpoint_unusable_rules_fallback",
                      error=str(exc))
            obs_metrics.SHIELD_TIER_TRANSITIONS.inc(tier="rules_fallback")
            from ..rca.streaming import StreamingScorer
            return StreamingScorer(self.builder.store, self.settings,
                                   mesh=self._serving_mesh())

    def _serving_mesh(self):
        """settings.mesh_dp > 1 -> a dp mesh (incident tables shard);
        settings.serve_graph_shards > 1 -> a (dp × graph) mesh whose
        graph axis carries the RESIDENT state itself (graft-fleet:
        node/feature/evidence tables + the GNN edge mirror split into
        graph partitions, mesh-resident ticks —
        parallel/sharded_streaming.py). None = single-device serving."""
        dp = max(int(self.settings.mesh_dp), 1)
        graph = max(int(getattr(self.settings, "serve_graph_shards", 1)), 1)
        if dp <= 1 and graph <= 1:
            return None
        import jax
        import numpy as _np
        from jax.sharding import Mesh
        from ..parallel.mesh import ensure_host_devices
        need = dp * graph
        ensure_host_devices(need)
        devices = jax.devices()
        if len(devices) < need:
            log.warning("serving_mesh_exceeds_devices", mesh_dp=dp,
                        serve_graph_shards=graph, devices=len(devices))
            return None
        if graph > 1:
            return Mesh(_np.array(devices[:need]).reshape(dp, graph),
                        ("dp", "graph"))
        return Mesh(_np.array(devices[:dp]), ("dp",))

    async def submit(self, incident: Incident) -> None:
        await self.queue.put(incident)

    async def _worker_loop(self, slot: int) -> None:
        while True:
            incident = await self.queue.get()
            if incident is None:
                self.queue.task_done()
                return
            try:
                # scorer construction tensorizes the whole store (O(N) +
                # device upload) — run it on an executor thread so the
                # one-time cold start never freezes the event loop. Once
                # resolved (warm scorer, or a backend with none), the
                # fast path reuses the cached reference: steady-state
                # incidents pay zero thread round-trips here
                # (graft-surge satellite). A stale surge pack (tenant
                # registered after the build) re-enters the slow path.
                if self._scorer_resolved and (
                        self.surge is None or self.surge.fresh()):
                    scorer = self.scorer
                else:
                    self.scorer_resolutions += 1
                    scorer = await asyncio.get_event_loop().run_in_executor(
                        None, self.serving_scorer)
                    self._scorer_resolved = True
                await run_incident_workflow(
                    incident, self.cluster, self.db, builder=self.builder,
                    settings=self.settings, engine=self.engine,
                    dedup=self.dedup, scorer=scorer, tenant=self.tenant)
                self.completed += 1
            except Exception as exc:  # graft-audit: allow[broad-except] per-incident isolation: one failed workflow must not kill the serve loop
                self.failed += 1
                log.error("incident_workflow_error", slot=slot,
                          incident=str(incident.id), error=str(exc))
            finally:
                self.queue.task_done()

    # -- graft-saga resumer: drain orphaned workflows ---------------------

    async def resume_orphans(self) -> int:
        """One sweep: reclaim workflows whose lease EXPIRED (their worker
        died mid-run) and re-enter them through run_incident_workflow's
        journal-replay path. Also stamps the stalled-workflow gauge
        (failed steps / exhausted resume budget) so operators see what
        the sweep will NOT touch."""
        if not getattr(self.settings, "workflow_lease_enabled", False):
            return 0
        max_resumes = int(getattr(self.settings, "workflow_max_resumes", 5))
        resumed = 0
        for row in self.db.orphaned_incidents(max_resumes=max_resumes):
            incident = _incident_from_row(row)
            obs_metrics.WORKFLOW_RESUMES.inc()
            self.resumed += 1
            resumed += 1
            log.info("workflow_resumed", incident=str(incident.id),
                     prior_resumes=row.get("resumes"))
            await self.submit(incident)
        obs_metrics.WORKFLOW_STALLED.set(float(len(
            self.db.stalled_workflows(max_resumes=max_resumes))))
        return resumed

    async def _resume_loop(self, interval_s: float) -> None:
        while True:
            await asyncio.sleep(interval_s)
            try:
                await self.resume_orphans()
            except (sqlite3Error, RuntimeError, ValueError) as exc:
                log.error("resume_sweep_failed", error=str(exc))

    async def start(self) -> None:
        if self.scorer is not None:
            # a prior drain() stopped the warms; serving is resuming, so
            # the compile-free guarantee must resume with it
            self.scorer.resume_warm()
            self.scorer._rearm_warm_growth()
        self._tasks = [asyncio.create_task(self._worker_loop(i))
                       for i in range(self.concurrency)]
        interval = float(getattr(self.settings,
                                 "workflow_resume_interval_s", 0.0))
        if interval > 0 and getattr(self.settings,
                                    "workflow_lease_enabled", False):
            self._resume_task = asyncio.create_task(
                self._resume_loop(interval))

    async def drain(self) -> None:
        """Wait for queue to empty, then stop workers."""
        if self._resume_task is not None:
            self._resume_task.cancel()
            self._resume_task = None
        await self.queue.join()
        for _ in self._tasks:
            await self.queue.put(None)
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks = []
        # stop_warm joins an in-flight XLA compile (seconds) — off-loop so
        # the event loop keeps serving callbacks meanwhile
        await asyncio.get_event_loop().run_in_executor(None, self.stop_warm)

    def stop_warm(self) -> None:
        """Cooperatively stop the background warm threads; bounded by at
        most one in-flight XLA compile."""
        if self.scorer is not None:
            self.scorer.stop_warm(join=True)
        if self._warm_thread is not None and self._warm_thread.is_alive():
            self._warm_thread.join()

    async def run_all(self, incidents: list[Incident]) -> dict:
        await self.start()
        for inc in incidents:
            await self.submit(inc)
        await self.drain()
        return {"completed": self.completed, "failed": self.failed}
