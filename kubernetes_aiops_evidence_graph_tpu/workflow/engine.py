"""Durable workflow engine — Temporal's job, in-process.

The reference leans on a Temporal server for durability: per-activity
retries with exponential backoff and non-retryable exception classes
(incident_workflow.py:60-72), per-step timeouts, event-history replay on
worker restart, and queryable in-flight state (:40-53). This engine
reproduces that contract with a SQLite step-journal (storage.sqlite
workflow_journal table): every step result is recorded, a re-run of the
same workflow id replays completed steps from the journal instead of
re-executing them, failed steps retry with backoff, and steps are expected
to be idempotent (SURVEY.md §5 checkpoint/resume).
"""
from __future__ import annotations

import asyncio
import hashlib
import inspect
import json
import time
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Sequence

from ..observability import WORKFLOW_STEP_DURATION, WORKFLOW_STEPS, TRACER, get_logger
from ..observability import metrics as obs_metrics
from ..observability.scope import SCOPE
from ..storage import Database

log = get_logger("workflow")


class NonRetryableError(Exception):
    """Fail the step immediately (reference non_retryable_error_types)."""


class WorkflowFenced(Exception):
    """graft-saga: this run's lease was lost (expired and reclaimed by
    another worker). The loser must stop driving the workflow at the next
    step boundary — the winner owns the journal now. Benign by design:
    the workflow continues elsewhere."""

    def __init__(self, workflow_id: str):
        super().__init__(f"lease for {workflow_id} lost; fenced out")
        self.workflow_id = workflow_id


@dataclass(frozen=True)
class RetryPolicy:
    """Reference defaults: 3 attempts, 1s → 5m exponential backoff
    (incident_workflow.py:60-72), plus deterministic seeded jitter."""
    max_attempts: int = 3
    initial_interval_s: float = 1.0
    backoff: float = 2.0
    max_interval_s: float = 300.0
    # ± fraction of the backoff applied as jitter. Seeded from the caller
    # key (workflow_id) + attempt, NOT from random(): a mass failure that
    # fails N workflows at once must not wake all N in lockstep on every
    # retry round (thundering herd), while a journal REPLAY of one
    # workflow must sleep exactly what the original run slept — replay
    # determinism is the Temporal-parity contract this engine keeps.
    jitter: float = 0.1

    non_retryable: tuple[type[Exception], ...] = (ValueError, TypeError,
                                                  NonRetryableError)

    def delay(self, attempt: int, key: "str | None" = None) -> float:
        base = min(self.initial_interval_s * self.backoff ** (attempt - 1),
                   self.max_interval_s)
        if not self.jitter or key is None:
            return base
        digest = hashlib.sha256(f"{key}:{attempt}".encode()).digest()
        u = int.from_bytes(digest[:8], "big") / float(1 << 64)  # [0, 1)
        return base * (1.0 - self.jitter + 2.0 * self.jitter * u)


@dataclass
class Step:
    name: str
    fn: Callable[..., Any]          # sync or async, takes (ctx) -> JSONable
    timeout_s: float = 30.0
    retry: RetryPolicy = field(default_factory=RetryPolicy)
    # skip the step (recorded as "skipped") when the predicate is false
    condition: Callable[[Any], bool] | None = None


class StepFailed(Exception):
    def __init__(self, step: str, cause: Exception, attempts: int):
        super().__init__(f"step {step} failed after {attempts} attempts: {cause}")
        self.step = step
        self.cause = cause
        self.attempts = attempts


class WorkflowEngine:
    """Executes a linear Step pipeline with journal-backed replay."""

    def __init__(self, db: Database, sleeper=asyncio.sleep) -> None:
        self.db = db
        self._sleep = sleeper  # injectable for tests

    async def run(self, workflow_id: str, steps: Sequence[Step], ctx: Any,
                  lease: "tuple[str, int] | None" = None,
                  lease_ttl_s: float = 60.0) -> dict:
        """Run (or resume) a workflow. Returns {step: result}. Completed
        steps in the journal are replayed, not re-executed.

        graft-saga: when ``lease=(owner, token)`` is supplied, the engine
        heartbeats the lease on a background task (so steps longer than
        the TTL — the 4h approval wait — stay covered) and FENCES at
        every step boundary: a heartbeat that no longer matches
        (owner, token) means the lease expired and another worker
        reclaimed the workflow, so this run raises WorkflowFenced instead
        of double-driving the journal."""
        journal = self.db.journal_get(workflow_id)
        results: dict[str, Any] = {}
        for entry_name, entry in journal.items():
            if entry["status"] in ("completed", "skipped"):
                results[entry_name] = entry["result"]
        if hasattr(ctx, "results"):
            ctx.results.update(results)

        hb_task: asyncio.Task | None = None
        if lease is not None:
            owner, token = lease

            async def _heartbeat() -> None:
                period = max(lease_ttl_s / 3.0, 0.02)
                while True:
                    await asyncio.sleep(period)
                    if not self.db.lease_heartbeat(workflow_id, owner,
                                                   token, lease_ttl_s):
                        return  # fenced; the boundary check raises

            hb_task = asyncio.get_event_loop().create_task(_heartbeat())
        try:
            for step in steps:
                if step.name in results:
                    log.debug("step_replayed", workflow=workflow_id,
                              step=step.name)
                    continue
                if lease is not None and not self.db.lease_heartbeat(
                        workflow_id, lease[0], lease[1], lease_ttl_s):
                    obs_metrics.WORKFLOW_LEASE_FENCED.inc()
                    log.warning("workflow_fenced", workflow=workflow_id,
                                step=step.name)
                    raise WorkflowFenced(workflow_id)
                if step.condition is not None and not step.condition(ctx):
                    self.db.journal_put(workflow_id, step.name, "skipped", None)
                    results[step.name] = None
                    if hasattr(ctx, "results"):
                        ctx.results[step.name] = None
                    continue
                result = await self._run_step(workflow_id, step, ctx)
                results[step.name] = result
                if hasattr(ctx, "results"):
                    ctx.results[step.name] = result
        finally:
            if hb_task is not None:
                hb_task.cancel()
        return results

    async def _run_step(self, workflow_id: str, step: Step, ctx: Any) -> Any:
        attempts = 0
        while True:
            attempts += 1
            self.db.journal_put(workflow_id, step.name, "running",
                                attempts=attempts)
            t0 = time.perf_counter()
            try:
                # graft-scope context propagation: the step span joins the
                # webhook's trace when this workflow's incident arrived
                # through one (ServeScope carries the webhook span context
                # across the async worker hop), so one exported trace
                # shows webhook → evidence → tick → verdict. Sync steps
                # run on executor threads whose span stack is empty —
                # attach() re-parents everything the step itself opens
                # (collector spans, serving-tick spans) under the step.
                with TRACER.span(f"workflow.{step.name}",
                                 parent=SCOPE.trace_parent(workflow_id),
                                 workflow=workflow_id) as step_span:
                    if inspect.iscoroutinefunction(step.fn):
                        result = await asyncio.wait_for(
                            step.fn(ctx), timeout=step.timeout_s)
                    else:
                        def _run_attached(fn=step.fn, span=step_span):
                            with TRACER.attach(span):
                                return fn(ctx)
                        try:
                            result = await asyncio.wait_for(
                                asyncio.get_event_loop().run_in_executor(
                                    None, _run_attached),
                                timeout=step.timeout_s)
                        except asyncio.TimeoutError:
                            # CAVEAT (graft-saga satellite): wait_for
                            # cancels the asyncio wrapper, but an executor
                            # THREAD cannot be cancelled — the step keeps
                            # running detached and its side effects may
                            # still land after this "timeout". Counted
                            # and logged so an orphan storm is visible;
                            # two-phase ledgered actions stay exactly-once
                            # regardless (the orphan's late result commit
                            # is an idempotent upsert).
                            obs_metrics.WORKFLOW_STEP_ORPHANS.inc(
                                step=step.name)
                            log.warning("step_thread_orphaned",
                                        workflow=workflow_id,
                                        step=step.name,
                                        timeout_s=step.timeout_s)
                            raise
                json.dumps(result, default=str)  # journal-serializable check
                dt = time.perf_counter() - t0
                WORKFLOW_STEP_DURATION.observe(dt, step=step.name)
                WORKFLOW_STEPS.inc(step=step.name, status="completed")
                # chaos boundary: the step's effects are live, its journal
                # commit is not — the classic lost-commit crash window
                inj = getattr(ctx, "faults", None)
                if inj is not None:
                    inj.at("journal_put")
                self.db.journal_put(workflow_id, step.name, "completed",
                                    result, attempts=attempts, duration_s=dt)
                return result
            except Exception as exc:
                dt = time.perf_counter() - t0
                WORKFLOW_STEP_DURATION.observe(dt, step=step.name)
                WORKFLOW_STEPS.inc(step=step.name, status="failed")
                retryable = not isinstance(exc, step.retry.non_retryable)
                log.warning("step_failed", workflow=workflow_id, step=step.name,
                            attempt=attempts, error=str(exc), retryable=retryable)
                if not retryable or attempts >= step.retry.max_attempts:
                    self.db.journal_put(workflow_id, step.name, "failed",
                                        {"error": str(exc)}, attempts=attempts,
                                        duration_s=dt)
                    raise StepFailed(step.name, exc, attempts) from exc
                await self._sleep(step.retry.delay(attempts,
                                                   key=workflow_id))

    def status(self, workflow_id: str) -> dict:
        """Queryable in-flight state (reference @workflow.query, :40-53)."""
        journal = self.db.journal_get(workflow_id)
        done = [s for s, e in journal.items() if e["status"] == "completed"]
        failed = [s for s, e in journal.items() if e["status"] == "failed"]
        running = [s for s, e in journal.items() if e["status"] == "running"]
        # graft-saga: lease + stalled visibility — the resumer and
        # operators must be able to SEE a workflow that is going nowhere
        # (failed step, or an expired lease nobody reclaimed yet)
        lease = self.db.lease_view(workflow_id)
        lease_expired = bool(
            lease and lease["deadline"] is not None
            and lease["deadline"] < time.time())  # graft-audit: allow[wall-clock] lease deadlines are cross-process wall-clock values (storage/sqlite._now)
        return {
            "workflow_id": workflow_id,
            "steps": journal,
            "completed": done,
            "failed": failed,
            "running": running,
            "state": self.db.rollup_state(
                len(failed), len(running), len(done)),
            "lease": lease,
            "stalled": bool(failed) or lease_expired,
        }
