"""Device-mesh construction.

The framework's two parallel axes (SURVEY.md §2.4):

* ``dp``    — data parallelism over incidents/graphs (the reference's
  "horizontally scalable Temporal workers", worker.py:43-61, reborn as a
  sharded batch dimension);
* ``graph`` — graph parallelism over node shards (the sequence/context-
  parallel analog: nodes are our tokens, halo/all-gather exchanges over ICI
  replace ring attention).

Collectives ride ICI within a slice and DCN across slices exactly as XLA
lays them out from the mesh axes; nothing here binds to hardware counts, so
the same code runs on a v5e pod slice or an 8-device virtual CPU mesh.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(dp: int | None = None, graph: int | None = None,
              devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if dp is None and graph is None:
        graph = 2 if n % 2 == 0 and n > 1 else 1
        dp = n // graph
    elif dp is None:
        dp = n // graph
    elif graph is None:
        graph = n // dp
    if dp * graph != n:
        raise ValueError(f"mesh {dp}x{graph} != {n} devices")
    arr = np.asarray(devices).reshape(dp, graph)
    return Mesh(arr, axis_names=("dp", "graph"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def dp_sharded(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("dp"))


def graph_sharded(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("graph"))
