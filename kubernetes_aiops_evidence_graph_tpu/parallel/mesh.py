"""Device-mesh construction.

The framework's two parallel axes (SURVEY.md §2.4):

* ``dp``    — data parallelism over incidents/graphs (the reference's
  "horizontally scalable Temporal workers", worker.py:43-61, reborn as a
  sharded batch dimension);
* ``graph`` — graph parallelism over node shards (the sequence/context-
  parallel analog: nodes are our tokens, halo/all-gather exchanges over ICI
  replace ring attention).

Collectives ride ICI within a slice and DCN across slices exactly as XLA
lays them out from the mesh axes; nothing here binds to hardware counts, so
the same code runs on a v5e pod slice or an 8-device virtual CPU mesh.
"""
from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_FORCE_FLAG = "xla_force_host_platform_device_count"


def _backend_initialized() -> bool:
    """True once jax has committed to a backend (after which the forced
    host-device count can no longer change for this process)."""
    try:
        from jax._src import xla_bridge
        return bool(xla_bridge._backends)
    except Exception:  # graft-audit: allow[broad-except] private-API probe: assume initialized when unsure
        return True


def ensure_host_devices(n: int) -> bool:
    """Honor the ``XLA_FLAGS=--xla_force_host_platform_device_count``
    fallback: make sure at least ``n`` devices exist, forcing virtual CPU
    host devices when the backend is not yet initialized. Returns True
    when ``n`` devices are (or will be) available — the sharded streaming
    paths and their registry entrypoints call this instead of raising
    ``SkipEntrypoint``/skipping outright, so CPU hosts exercise the mesh
    code hermetically (tests/conftest.py pre-forces 8; this covers bare
    scripts and the analysis CLI too)."""
    if n <= 1:
        return True
    if _backend_initialized():
        return len(jax.devices()) >= n
    flags = os.environ.get("XLA_FLAGS", "")
    if _FORCE_FLAG in flags:
        # a count is already requested; honor it rather than fight it
        try:
            want = int(flags.split(f"--{_FORCE_FLAG}=", 1)[1].split()[0])
        except (IndexError, ValueError):
            return len(jax.devices()) >= n
        return want >= n or len(jax.devices()) >= n
    os.environ["XLA_FLAGS"] = (flags + f" --{_FORCE_FLAG}={n}").strip()
    return True


class MeshUnavailable(RuntimeError):
    """``serve_graph_shards`` exceeds what the device pool (after the
    forced-host-device fallback) can carry. Raised only on the strict
    path — the serving scorer keeps its logged single-device fallback,
    but callers that must not silently degrade (benches, heal planning,
    operators asserting a fleet) get a clear error instead of a
    misshaped or missing mesh."""


def serving_mesh(graph: int, devices=None,
                 strict: bool = False) -> "Mesh | None":
    """(1 x graph) serving mesh for the graph-sharded streaming scorer
    (settings.serve_graph_shards). None when the device pool cannot carry
    the axis — callers fall back to single-device serving (logged by the
    scorer, never silent). ``strict=True`` raises
    :class:`MeshUnavailable` (with the requested vs available counts)
    instead of returning None."""
    if graph <= 1:
        return None
    if devices is None:
        if not ensure_host_devices(graph):
            if strict:
                raise MeshUnavailable(
                    f"serve_graph_shards={graph} exceeds the "
                    f"{len(jax.devices())} available devices (forced-host "
                    "fallback cannot mint devices after backend init)")
            return None
        devices = jax.devices()
    if len(devices) < graph:
        if strict:
            raise MeshUnavailable(
                f"serve_graph_shards={graph} exceeds the {len(devices)} "
                "available devices")
        return None
    arr = np.asarray(devices[:graph]).reshape(1, graph)
    return Mesh(arr, axis_names=("dp", "graph"))


def make_mesh(dp: int | None = None, graph: int | None = None,
              devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if dp is None and graph is None:
        graph = 2 if n % 2 == 0 and n > 1 else 1
        dp = n // graph
    elif dp is None:
        dp = n // graph
    elif graph is None:
        graph = n // dp
    if dp * graph != n:
        raise ValueError(f"mesh {dp}x{graph} != {n} devices")
    arr = np.asarray(devices).reshape(dp, graph)
    return Mesh(arr, axis_names=("dp", "graph"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def dp_sharded(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("dp"))


def graph_sharded(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("graph"))
