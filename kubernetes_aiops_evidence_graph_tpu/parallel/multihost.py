"""Multi-host bootstrap — the DCN half of the communication backend.

The reference scales horizontally by adding Temporal worker containers
against a shared server (worker.py:43-61, docker-compose.yml). The TPU
equivalent is a *SPMD process group*: every host runs this same program,
`jax.distributed.initialize` wires the controller, and a mesh whose outer
axis spans hosts makes XLA route that axis's collectives over DCN while
inner axes stay on ICI (scaling-book recipe; SURVEY.md §2.4/§5
"Distributed communication backend").

Design rule encoded here: put ``dp`` (incidents) on the host axis — DP
gradients/score merges are one psum per step and tolerate DCN latency —
and keep ``graph`` (per-layer halo exchanges) inside a slice on ICI.

Usage (same command on every host, env-configured):

    KAEG_COORDINATOR=host0:9876 KAEG_NUM_PROCESSES=4 KAEG_PROCESS_ID=$i \
        python -m kubernetes_aiops_evidence_graph_tpu.serve

On single-host (or under the driver's virtual CPU mesh) everything here
degrades to a no-op and `make_multihost_mesh` equals `make_mesh`.
"""
from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import make_mesh


def init_distributed() -> bool:
    """Initialize the JAX process group from KAEG_* env, if configured.

    Returns True when running multi-process after the call. TPU pod slices
    auto-discover (initialize() with no args); explicit env wins so the
    same entrypoint also works on CPU/GPU fleets."""
    coordinator = os.environ.get("KAEG_COORDINATOR", "")
    num = int(os.environ.get("KAEG_NUM_PROCESSES", "0") or 0)
    pid = int(os.environ.get("KAEG_PROCESS_ID", "-1") or -1)
    if coordinator and num > 1 and pid >= 0:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num,
            process_id=pid,
        )
        return True
    if os.environ.get("KAEG_AUTO_DISTRIBUTED", "") == "1":
        jax.distributed.initialize()  # TPU pod auto-discovery
        return jax.process_count() > 1
    return False


def make_multihost_mesh(graph_per_host: int | None = None) -> Mesh:
    """(dp × graph) mesh with dp spanning hosts (DCN) and graph local (ICI).

    Each host contributes its local devices to the graph axis; the dp axis
    length equals the host count × any leftover local factor. With one
    process this is exactly `make_mesh()`."""
    if jax.process_count() == 1:
        return make_mesh()
    local = jax.local_device_count()
    graph = graph_per_host or local
    if local % graph != 0:
        raise ValueError(
            f"graph_per_host={graph} must divide local devices {local}")
    # global device array ordered host-major: hosts × local -> (dp, graph)
    devices = np.asarray(jax.devices())  # sorted by (process_index, local id)
    dp = devices.size // graph
    return Mesh(devices.reshape(dp, graph), axis_names=("dp", "graph"))


def host_local_incident_slice(num_incidents: int) -> slice:
    """Which incident rows this host feeds (dp is the host axis): contiguous
    block partitioning with the tail on the last host."""
    n, k = jax.process_count(), jax.process_index()
    per = -(-num_incidents // n)  # ceil
    return slice(k * per, min((k + 1) * per, num_incidents))
