"""graft-fleet — the RESIDENT streaming serving state sharded over the mesh.

The single-device serving pass sits at 91% of its bandwidth roofline
(BENCH_r05): the remaining scaling axis is OUT, not up. One
``StreamingScorer``/``GnnStreamingScorer`` holds one donated resident
mirror on one chip, capping the servable fleet at a single device's HBM.
This module extends the donated tick state across a ``graph`` mesh axis
of D devices (``settings.serve_graph_shards``) so one v5e-8 slice serves
a 500k-pod fleet from a single resident sharded state:

* **State layout.** Node-addressed tables (features, kind, nmask) keep
  their GLOBAL shapes and shard into D contiguous node blocks via
  ``NamedSharding(mesh, P("graph"))`` — the same owner assignment as the
  batch partitioner (parallel/partition.py: owner = row // (Pn/D)). The
  GNN edge mirror becomes D per-shard relation-bucketed regions stacked
  in one [D·Pe_shard] slot space (owner shard = slot // Pe_shard; edges
  live on their DESTINATION's owner, so the message scatter is always
  shard-local). Evidence tables stay ``P("dp")`` (replicated across the
  graph axis on the (1 x D) serving mesh).

* **Delta routing.** The host delta-packing stage routes each delta
  batch to its owner shard with PER-SHARD ``_DELTA_BUCKETS`` sub-buckets
  (``route_node_delta``): the compiled delta width is the max over
  shards, so one hot shard doesn't retrace the others, and within each
  shard deltas keep store-journal order (the insertion order of the
  pending dict / pending-edge map) — replay determinism is a routing
  invariant, tested by the sort-contract test.

* **Ticks.** ``sharded_rules_tick`` scatters locally, folds ONLY the
  slots whose node lives in its own block (the shared
  evidence_fold_block), and reduces verdicts with ONE small psum of the
  concatenated [rows, DIM + pair_width] counts — strictly less traffic
  than a ring of D ppermutes of [Pn/D, DIM] blocks, and bit-identical to
  the single-device fold (out-of-block slots contribute exact zeros;
  adding zeros never rounds). ``sharded_gnn_tick`` scatters its per-shard
  deltas locally, then runs the ring-halo message pass: each layer
  ASSEMBLES the [Pe_shard, H] source rows over D ``ppermute`` hops of the
  [Pn/D, H] embedding block (each slot's row arrives from exactly one
  block; the masked adds are exact), then runs the SAME fused
  gather→matmul→segment kernel the single-device tick runs, shard-local.
  The readout streams incident embeddings out of the ring (one more set
  of D hops) — exactly ``(LAYERS+1)·D`` ppermutes of [N/D, H] blocks per
  tick and ZERO [N, H] all-gathers, the same contract the snapshot
  kernels already obey (CostSpec-pinned: analysis/registry.py
  ``streaming.gnn_tick.sharded``).

* **Parity.** The rules tick is BIT-identical to the single-device
  scorer at every shard count and pipeline depth
  (tests/test_sharded_streaming.py). The GNN tick is bit-identical
  across pipeline depths and across crash/recovery at a fixed D — the
  per-shard mirror layout is a pure function of the store journal — and
  verdict-identical to D=1 with probs at float tolerance (the per-shard
  slot allocation orders per-dst message sums differently; same contract
  as the sharded snapshot kernels, parallel/sharded_gnn.py docstring).

* **Donation.** Both ticks donate their resident arrays exactly like the
  single-device ticks (`tick-donation` audit rule): the sharded mirror
  is scattered in place per shard, never reallocated.
"""
from __future__ import annotations

from functools import lru_cache

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..observability import scope as obs_scope
from .compat import shard_map
from .sharded_gnn import _ring_perm
from .sharded_rules import evidence_fold_block


def owner_of(rows, nodes_per_shard: int):
    """Owner shard of each global node row — the contiguous-block
    assignment of parallel/partition.py."""
    return np.asarray(rows, np.int64) // int(nodes_per_shard)


def route_node_delta(entries, nodes_per_shard: int, shards: int,
                     buckets: tuple[int, ...]):
    """Route host-side node deltas to their owner shards with per-shard
    sub-buckets.

    ``entries`` is an iterable of ``(global_row, payload...)`` tuples in
    STORE-JOURNAL order. Returns ``(idx, payload_lists, pk)`` where
    ``idx`` is [D, pk] of SHARD-LOCAL rows (padding = the out-of-range
    sentinel ``nodes_per_shard``, dropped by the on-device scatter),
    ``payload_lists`` is a list of per-shard payload lists aligned with
    the live prefix of each shard's row, and ``pk`` is the shared static
    sub-bucket width — ``bucket_for`` of the MAX per-shard count, so one
    hot shard doesn't retrace the others. Within each shard the journal
    order is preserved verbatim (the sort-contract invariant: replay
    determinism depends on it)."""
    from ..utils.padding import bucket_for
    per_shard: list[list] = [[] for _ in range(shards)]
    for e in entries:
        g = int(e[0]) // nodes_per_shard
        per_shard[g].append(e)
    k = max((len(s) for s in per_shard), default=0)
    # graft-scope: per-shard routing counts — the imbalance gauge (one
    # hot shard sets the compiled delta width for every shard) and the
    # shard_rows field of the next tick's flight record
    obs_scope.note_route(len(s) for s in per_shard)
    pk = bucket_for(max(k, 1), buckets)
    idx = np.full((shards, pk), nodes_per_shard, np.int32)
    for g, ents in enumerate(per_shard):
        for j, e in enumerate(ents):
            idx[g, j] = int(e[0]) - g * nodes_per_shard
    return idx, per_shard, pk


@lru_cache(maxsize=None)
def sharded_rules_tick(mesh, nodes_per_shard: int, rows_per_shard: int,
                       pair_width: int, pk: int, rk: int, width: int):
    """Graph-sharded fused rules tick (replaces the ring `_graph_tick`).

    Per-shard packed delta layout (one [D, L] int32 transfer, in_spec
    P("graph") — the row-delta payload rides duplicated in every shard's
    row, its entries are [rk]-scale and the duplication is what keeps the
    tick at two host→device transfers):

      ints[g] = [ f_idx pk (SHARD-LOCAL, sentinel=nps) |
                  r_idx rk | r_cnt rk | r_ev rk·W | r_pair rk·W ]

    Each shard scatters its own feature-delta rows, scatters the
    (dp-local) evidence-row delta, folds ONLY the slots whose node lives
    in its own block, and ONE psum of the concatenated
    [rows, DIM + pair_width] counts completes the fold — the
    owner-fold + verdict-psum layout: zero ppermutes, zero all-gathers,
    bit-identical to the single-device fold (out-of-block slots fold
    exact zeros)."""
    from ..rca.tpu_backend import finish_scores

    def local_rules_tick(features, ints, f_rows, ev_idx, ev_cnt, ev_pair,
                         chain):
        ints, f_rows = ints[0], f_rows[0]    # [1, ...] graph-shard block
        f_idx = ints[:pk]                    # already shard-local
        r_idx = ints[pk:pk + rk]
        r_cnt = ints[pk + rk:pk + 2 * rk]
        off = pk + 2 * rk
        r_ev = ints[off:off + rk * width].reshape(rk, width)
        r_pair = ints[off + rk * width:
                      off + 2 * rk * width].reshape(rk, width)

        features = features.at[f_idx].set(f_rows, mode="drop")

        lo_r = jax.lax.axis_index("dp") * rows_per_shard
        rl = jnp.where((r_idx >= lo_r) & (r_idx < lo_r + rows_per_shard),
                       r_idx - lo_r, rows_per_shard)
        ev_idx = ev_idx.at[rl].set(r_ev, mode="drop")
        ev_cnt = ev_cnt.at[rl].set(r_cnt, mode="drop")
        ev_pair = ev_pair.at[rl].set(r_pair, mode="drop")

        lo_n = jax.lax.axis_index("graph") * nodes_per_shard
        counts, pair_counts = evidence_fold_block(
            features, ev_idx, ev_cnt, ev_pair, lo_n,
            nodes_per_shard=nodes_per_shard, pair_width=pair_width,
            rows_per_shard=rows_per_shard)
        # ONE small collective completes the fold: [rows, DIM+PW] psum
        # over the graph axis (vs D ppermutes of [Pn/D, DIM] blocks in
        # the ring formulation — the evidence fold needs every block's
        # contribution, not the blocks themselves)
        folded = jax.lax.psum(
            jnp.concatenate([counts, pair_counts], axis=1), "graph")
        counts = folded[:, :counts.shape[1]]
        pair_counts = folded[:, counts.shape[1]:]
        counts = counts + jnp.minimum(chain, 0.0)[:, None]
        return (features, ev_idx, ev_cnt, ev_pair) + finish_scores(
            counts, pair_counts.max(axis=1), rows_per_shard)

    g, d = P("graph"), P("dp")
    rules_tick = shard_map(
        local_rules_tick, mesh=mesh,
        in_specs=(g, g, g, d, d, d, d),
        out_specs=(g, d, d, d) + (d,) * 7,
        check_vma=False,
    )
    # same donation contract as the single-device _tick: the resident
    # state flows through, so the sharded tick must not reallocate it
    return jax.jit(rules_tick, donate_argnums=(0, 3, 4, 5))


@lru_cache(maxsize=None)
def sharded_gnn_tick(mesh, nodes_per_shard: int, pe_shard: int, pi: int,
                     pk: int, ek: int, rel_offsets=None,
                     slices_sorted: bool = False, compute_dtype=None,
                     use_pallas: bool = False):
    """Graph-sharded fused GNN streaming tick: the mesh-resident analog of
    rca/gnn_streaming._gnn_tick.

    Resident per-shard state (all donated except params/features): the
    aux tables kind/nmask shard with the features ([Pn] P("graph") node
    blocks); the edge mirror is D per-shard relation-bucketed regions
    stacked in one [D·Pe_shard] slot space (P("graph"): shard g owns
    slots [g·Pe_shard, (g+1)·Pe_shard)) holding GLOBAL src ids and LOCAL
    dst rows — every edge lives on its destination's owner, so the
    segment-sum is always shard-local.

    Per-shard packed delta ([D, L] int32, one transfer; incident tables
    ride replicated in every shard's row — they are [Pi]-scale):

      ints[g] = [ f_idx pk (local, sentinel=nps) | kind_v pk | nmask_v pk |
                  e_idx ek (local slot, sentinel=Pe_shard) | e_src ek |
                  e_dst ek (local) | e_rel ek | e_mask ek |
                  inc_nodes pi (global) | inc_mask pi ]

    Each tick: local delta scatters, then the ring-halo message pass —
    per layer, the [Pe_shard, H] source rows are ASSEMBLED over D
    ``ppermute`` hops of the [Pn/D, H] embedding block (each slot's row
    arrives from exactly ONE block; the masked adds are exact, so the
    assembled rows are bit-identical to a global gather), and the SAME
    fused gather→matmul→segment kernel as the single-device tick runs
    shard-local. The readout streams incident embeddings out of the ring:
    exactly (LAYERS+1)·D ppermutes of [N/D, H] blocks per tick, zero
    [N, H] all-gathers, zero psums (CostSpec-pinned).

    graft-fuse: ``use_pallas=True`` (settings.gnn_fused_tick) promotes
    the SHARD-LOCAL portion — the per-layer gather→matmul→segment over
    the assembled rows — to the tiled VMEM-resident Pallas kernel
    (bit-identical fold), while the halo assembly and the readout ring
    stay in XLA: the collective census the CostSpec pins is unchanged,
    only the shard-local lowering is. Layouts off the EDGE_TILE ladder
    fall back through the Pallas dispatcher's own XLA fallback."""
    from ..ops.segment import gather_matmul_segment
    from ..rca import gnn

    if use_pallas:
        from ..ops.pallas_segment import pallas_gather_matmul_segment
        gms_local = pallas_gather_matmul_segment
    else:
        gms_local = gather_matmul_segment

    g_size = mesh.shape["graph"]

    def _assemble_ring(h_local, esrc):
        """[Pe_shard, H] source rows for this shard's edges, assembled
        over one full rotation of the embedding blocks. Padded slots
        (esrc=0, mask 0) assemble block 0's row and are zeroed by the
        kernel's mask."""
        my = jax.lax.axis_index("graph")

        def body(r, carry):
            h_block, rows = carry
            src_shard = jnp.mod(my - r, g_size)
            lo = src_shard * nodes_per_shard
            in_blk = ((esrc >= lo) & (esrc < lo + nodes_per_shard)
                      ).astype(h_block.dtype)
            local = jnp.clip(esrc - lo, 0, nodes_per_shard - 1)
            rows = rows + h_block[local] * in_blk[:, None]
            h_block = jax.lax.ppermute(h_block, "graph", _ring_perm(g_size))
            return h_block, rows

        _, rows = jax.lax.fori_loop(
            0, g_size, body,
            (h_local, jnp.zeros((pe_shard, h_local.shape[1]),
                                h_local.dtype)))
        return rows

    def _readout_ring(h_local, inc_nodes):
        """Stream incident-node embeddings out of the ring — the
        (LAYERS+1)'th set of D hops; complete (and identical) on every
        shard after the rotation."""
        my = jax.lax.axis_index("graph")

        def body(r, carry):
            h_block, emb = carry
            src_shard = jnp.mod(my - r, g_size)
            lo = src_shard * nodes_per_shard
            in_blk = ((inc_nodes >= lo)
                      & (inc_nodes < lo + nodes_per_shard)
                      ).astype(h_block.dtype)
            local = jnp.clip(inc_nodes - lo, 0, nodes_per_shard - 1)
            emb = emb + h_block[local] * in_blk[:, None]
            h_block = jax.lax.ppermute(h_block, "graph", _ring_perm(g_size))
            return h_block, emb

        _, emb = jax.lax.fori_loop(
            0, g_size, body,
            (h_local, jnp.zeros((pi, h_local.shape[1]), h_local.dtype)))
        return emb

    def local_gnn_tick(params, features, kind, nmask, esrc, edst, erel,
                       emask, ints):
        ints = ints[0]                       # [1, L] graph-shard block
        f_idx = ints[:pk]                    # already shard-local
        kind_v = ints[pk:2 * pk]
        nmask_v = ints[2 * pk:3 * pk].astype(jnp.float32)
        o = 3 * pk
        e_idx = ints[o:o + ek]               # already region-local
        e_src = ints[o + ek:o + 2 * ek]
        e_dst = ints[o + 2 * ek:o + 3 * ek]
        e_rel = ints[o + 3 * ek:o + 4 * ek]
        e_mask = ints[o + 4 * ek:o + 5 * ek].astype(jnp.float32)
        o += 5 * ek
        inc_nodes = ints[o:o + pi]
        inc_mask = ints[o + pi:o + 2 * pi].astype(jnp.float32)

        kind = kind.at[f_idx].set(kind_v, mode="drop")
        nmask = nmask.at[f_idx].set(nmask_v, mode="drop")
        esrc = esrc.at[e_idx].set(e_src, mode="drop")
        edst = edst.at[e_idx].set(e_dst, mode="drop")
        erel = erel.at[e_idx].set(e_rel, mode="drop")
        emask = emask.at[e_idx].set(e_mask, mode="drop")

        # local degree of local dst rows (every dst's edges live here)
        deg = jnp.zeros(nodes_per_shard, features.dtype
                        ).at[edst].add(emask, mode="drop")
        inv_deg = jnp.where(deg > 0, 1.0 / jnp.maximum(deg, 1.0), 0.0)

        h = jax.nn.relu(features @ params["embed_w"] + params["embed_b"]
                        + params["kind_emb"][kind])
        h = h * nmask[:, None]
        src_iota = jax.lax.iota(jnp.int32, pe_shard)
        for layer in params["layers"]:
            rows = _assemble_ring(h, esrc)
            agg = gms_local(
                rows, layer["w_rel"], src_iota, edst, emask,
                rel_offsets, nodes_per_shard,
                slices_sorted=slices_sorted,
                compute_dtype=compute_dtype) * inv_deg[:, None]
            if compute_dtype is not None:
                self_t = jax.lax.dot(h.astype(compute_dtype),
                                     layer["w_self"].astype(compute_dtype),
                                     preferred_element_type=h.dtype)
            else:
                self_t = h @ layer["w_self"]
            h = jax.nn.relu(self_t + agg + layer["b"]) + h

        emb = _readout_ring(h, inc_nodes)
        logits = emb @ params["head_w"] + params["head_b"]
        probs = jax.nn.softmax(logits, axis=-1)
        # mask dead incident rows so a stale row can never surface a score
        probs = probs * inc_mask[:, None]
        return kind, nmask, esrc, edst, erel, emask, logits, probs

    g, r = P("graph"), P()
    gnn_tick = shard_map(
        local_gnn_tick, mesh=mesh,
        in_specs=(r, g, g, g, g, g, g, g, g),
        # logits/probs are complete AND identical on every shard after
        # the readout ring — replicated outputs
        out_specs=(g,) * 6 + (r, r),
        check_vma=False,
    )
    # donation contract of _gnn_tick: the resident mirror (kind/nmask +
    # the four edge regions) is donated; params and the base scorer's
    # features must survive the tick
    return jax.jit(gnn_tick, donate_argnums=(2, 3, 4, 5, 6, 7))


def shared_shard_offsets(counts_by_shard: np.ndarray, slack: float,
                         min_cap: int) -> tuple[int, ...]:
    """Shared per-shard relation-slice offsets: capacity per relation is
    the MAX live count over shards, bucketed — one static offsets tuple
    describes EVERY shard's region (the partition.py contract), which is
    what lets the shard_map'd tick compile once."""
    from ..graph.snapshot import rel_slice_offsets
    counts = np.asarray(counts_by_shard, np.int64)
    return rel_slice_offsets(counts.max(axis=0), slack=slack,
                             min_cap=min_cap)
