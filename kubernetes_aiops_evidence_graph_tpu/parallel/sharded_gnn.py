"""Multi-chip GNN training step — shard_map over a (dp × graph) mesh.

The distributed design (SURVEY.md §2.4, scaling-book recipe: pick a mesh,
annotate shardings, let XLA place collectives):

* node embeddings are computed shard-locally on the ``graph`` axis; each
  layer then performs a **halo exchange** so every shard can read the
  source side of its incoming edges — the node-parallel (sequence/context-
  parallel analog) dimension, riding ICI. Two interchangeable strategies:

  - ``halo="allgather"``: one all-gather of the full [N, H] embedding
    matrix per layer. Simple, minimum latency at small N.
  - ``halo="ring"``: the ring-attention analog — D-1 ``ppermute`` steps
    stream neighbor shards' [N/D, H] blocks around the ring; each step
    accumulates messages from edges whose source lives in the block in
    flight, overlapping compute with communication and never
    materializing more than one remote block (O(N/D) memory vs O(N)).
    This is what makes 50k+-node graphs fit when H or D grows.

* each graph shard scatter-adds messages only into its own node range
  (edges were host-partitioned by destination, partition.py);
* incidents are read out on the ``dp`` axis (ring mode streams the
  readout too); the loss is a masked mean **psum'd over both axes**;
* `jax.grad` differentiates straight through shard_map, so gradient
  collectives (psum of the all-gather transpose = reduce-scatter; the
  ppermute transpose = counter-rotation) are inserted by XLA
  automatically; parameters stay replicated.

Both halo strategies support BOTH relation-kernel mappings (rca/gnn.py
module docstring): pass ``rel_offsets`` (the PartitionedGraph's shared
per-shard slice table, a static tuple) to run the relation-bucketed
kernel — per-slice gather → one [H, H] matmul per relation → shard-local
segment-sum; omit it for the transform-then-gather reference. The
reference mode stays bit-identical to single-device (one shared kernel,
same edge order); the bucketed mode accumulates per relation slice, whose
per-shard edge order differs from the single-device layout, so parity is
within float tolerance (~1e-5 on the loss) rather than bit-exact — pinned
by tests/test_parallel.py.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .compat import shard_map
from ..ops.segment import gather_matmul_segment
from ..rca import gnn


def _ring_perm(d: int) -> list[tuple[int, int]]:
    return [(i, (i + 1) % d) for i in range(d)]


def _ring_messages(h_local, w_rel, esrc, erel, emask, edst_local, d: int,
                   rel_offsets=None, slices_sorted: bool = False):
    """Ring halo exchange; relation kernel per ``rel_offsets`` (module
    docstring). Reference mode is the transform-then-gather mapping (same
    rewrite as gnn._message_pass — TPU scatters serialize, so
    per-(dst, relation) scatter buckets measured 9.4x slower): each step
    transforms the in-flight block by ALL R relation matrices (one MXU
    einsum), every in-block edge gathers its rel-specific source row, and
    aggregation stays a single [E, H] segment-sum into local dst rows.
    Bucketed mode replaces that with the fused per-slice gather-matmul-
    segment kernel over the in-flight block (mask = emask * in_block).
    Either way the ring moves only [nps, H] blocks — communication is
    unchanged.

    Step r holds shard ((my - r) mod d)'s embedding block; edges whose
    global src index falls in that shard's range consume it, then the block
    rotates one hop around the ring (ppermute over 'graph')."""
    nps = h_local.shape[0]
    my = jax.lax.axis_index("graph")
    rel = erel   # rel_messages clips internally

    def body(r, carry):
        h_block, agg = carry
        src_shard = jnp.mod(my - r, d)
        lo = src_shard * nps
        in_block = ((esrc >= lo) & (esrc < lo + nps)).astype(h_block.dtype)
        local_src = jnp.clip(esrc - lo, 0, nps - 1)
        if rel_offsets is not None:
            agg = agg + gather_matmul_segment(
                h_block, w_rel, local_src, edst_local, emask * in_block,
                rel_offsets, nps, slices_sorted=slices_sorted)
        else:
            msg = gnn.rel_messages(h_block, w_rel, local_src, rel,
                                   emask * in_block)
            agg = agg.at[edst_local].add(msg)
        h_block = jax.lax.ppermute(h_block, "graph", _ring_perm(d))
        return h_block, agg

    _, agg = jax.lax.fori_loop(
        0, d, body, (h_local, jnp.zeros_like(h_local)))
    return agg


def _ring_readout(h_local, inc_nodes, d: int):
    """Stream incident-node embeddings out of the ring (no all-gather)."""
    nps = h_local.shape[0]
    my = jax.lax.axis_index("graph")

    def body(r, carry):
        h_block, emb = carry
        src_shard = jnp.mod(my - r, d)
        lo = src_shard * nps
        in_block = ((inc_nodes >= lo) & (inc_nodes < lo + nps)
                    ).astype(h_block.dtype)
        local = jnp.clip(inc_nodes - lo, 0, nps - 1)
        emb = emb + h_block[local] * in_block[:, None]
        h_block = jax.lax.ppermute(h_block, "graph", _ring_perm(d))
        return h_block, emb

    _, emb = jax.lax.fori_loop(
        0, d, body,
        (h_local, jnp.zeros((inc_nodes.shape[0], h_local.shape[1]),
                            h_local.dtype)))
    return emb


def _sharded_loss(mesh: Mesh, halo: str = "allgather", rel_offsets=None,
                  slices_sorted: bool = False):
    """Build the shard_map'd loss over local shards. ``rel_offsets`` (the
    PartitionedGraph's shared static slice table) selects the
    relation-bucketed kernel for both halo strategies."""
    if halo not in ("allgather", "ring"):
        raise ValueError(f"halo must be allgather|ring, got {halo!r}")
    graph_size = mesh.shape["graph"]
    if rel_offsets is not None:
        rel_offsets = tuple(int(o) for o in rel_offsets)

    def local_loss(params, feats, kind, nmask, esrc, edst_local, erel,
                   emask, inc_nodes, inc_mask, labels):
        # strip the leading shard axis of size 1 that shard_map hands us
        feats, kind, nmask = feats[0], kind[0], nmask[0]
        esrc, edst_local = esrc[0], edst_local[0]
        erel, emask = erel[0], emask[0]
        inc_nodes, inc_mask, labels = inc_nodes[0], inc_mask[0], labels[0]

        # local degree of local dst nodes
        nps = feats.shape[0]
        deg = jnp.zeros(nps, feats.dtype).at[edst_local].add(emask)
        inv_deg = jnp.where(deg > 0, 1.0 / jnp.maximum(deg, 1.0), 0.0)

        h_local = jax.nn.relu(
            feats @ params["embed_w"] + params["embed_b"] + params["kind_emb"][kind]
        ) * nmask[:, None]

        for layer in params["layers"]:
            # halo exchange: every shard needs src embeddings of its
            # in-edges. Both strategies support both relation mappings
            # (see _ring_messages / gnn module docstring); the all-gather
            # still moves only [N, H] — per-relation compute is
            # recomputed shard-locally (replicated FLOPs are MXU-cheap,
            # replicated comm is not)
            if halo == "ring":
                agg = _ring_messages(h_local, layer["w_rel"], esrc, erel,
                                     emask, edst_local, graph_size,
                                     rel_offsets=rel_offsets,
                                     slices_sorted=slices_sorted)
            elif rel_offsets is not None:
                h_full = jax.lax.all_gather(h_local, "graph", tiled=True)
                agg = gather_matmul_segment(
                    h_full, layer["w_rel"], esrc, edst_local, emask,
                    rel_offsets, nps, slices_sorted=slices_sorted)
            else:
                h_full = jax.lax.all_gather(h_local, "graph", tiled=True)
                msg = gnn.rel_messages(h_full, layer["w_rel"], esrc, erel,
                                       emask)
                agg = jnp.zeros_like(h_local).at[edst_local].add(msg)
            agg = agg * inv_deg[:, None]
            h_local = jax.nn.relu(
                h_local @ layer["w_self"] + agg + layer["b"]
            ) + h_local

        if halo == "ring":
            emb = _ring_readout(h_local, inc_nodes, graph_size)
        else:
            h_full = jax.lax.all_gather(h_local, "graph", tiled=True)
            emb = h_full[inc_nodes]
        logits = emb @ params["head_w"] + params["head_b"]         # [B/D, C]
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
        # incidents are dp-sharded; graph shards all compute the same readout
        loss_sum = jax.lax.psum((nll * inc_mask).sum(), "dp")
        count = jax.lax.psum(inc_mask.sum(), "dp")
        return (loss_sum / jnp.maximum(count, 1.0))[None]

    return shard_map(
        local_loss,
        mesh=mesh,
        in_specs=(
            P(),                      # params replicated
            P("graph"), P("graph"), P("graph"),               # nodes
            P("graph"), P("graph"), P("graph"), P("graph"),   # edges
            P("dp"), P("dp"), P("dp"),                        # incidents
        ),
        out_specs=P("graph"),  # per-graph-shard copy of the scalar loss
        check_vma=False,
    )


def make_sharded_train_step(mesh: Mesh, tx, halo: str = "allgather",
                            rel_offsets=None, slices_sorted: bool = False):
    """jitted (params, opt_state, part: PartitionedGraph arrays) -> step.
    Pass ``rel_offsets=part.rel_offsets`` to train on the
    relation-bucketed kernel (see _sharded_loss)."""
    sharded_loss = _sharded_loss(mesh, halo=halo, rel_offsets=rel_offsets,
                                 slices_sorted=slices_sorted)

    def loss_scalar(params, *arrs):
        return sharded_loss(params, *arrs).mean()

    # same donation discipline as the single-device step (rca/gnn.py):
    # params/opt_state are rebound every call, graph/incident arrays are
    # not — donate exactly the consumed pair
    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, feats, kind, nmask, esrc, edst, erel,
             emask, inc_nodes, inc_mask, labels):
        loss, grads = jax.value_and_grad(loss_scalar)(
            params, feats, kind, nmask, esrc, edst, erel, emask,
            inc_nodes, inc_mask, labels)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    return step


def device_put_partitioned(part, mesh: Mesh) -> tuple:
    """Place PartitionedGraph arrays with their mesh shardings."""
    g = NamedSharding(mesh, P("graph"))
    d = NamedSharding(mesh, P("dp"))
    put = jax.device_put
    return (
        put(part.features, g), put(part.node_kind, g), put(part.node_mask, g),
        put(part.edge_src, g), put(part.edge_dst_local, g),
        put(part.edge_rel, g), put(part.edge_mask, g),
        put(part.incident_nodes, d), put(part.incident_mask, d), put(part.labels, d),
    )
