"""Multi-chip GNN training step — shard_map over a (dp × graph) mesh.

The distributed design (SURVEY.md §2.4, scaling-book recipe: pick a mesh,
annotate shardings, let XLA place collectives):

* node embeddings are computed shard-locally on the ``graph`` axis, then
  **all-gathered over 'graph'** once per layer so every shard can read the
  source side of its incoming edges — the halo exchange of our node-
  parallel (sequence-parallel analog) dimension, riding ICI;
* each graph shard scatter-adds messages only into its own node range
  (edges were host-partitioned by destination, partition.py);
* incidents are read out on the ``dp`` axis from the gathered embeddings;
  the loss is a masked mean **psum'd over both axes**;
* `jax.grad` differentiates straight through shard_map, so gradient
  collectives (psum of the all-gather transpose = reduce-scatter) are
  inserted by XLA automatically; parameters stay replicated.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from ..rca import gnn


def _sharded_loss(mesh: Mesh):
    """Build the shard_map'd loss over local shards."""

    def local_loss(params, feats, kind, nmask, esrc, edst_local, emask,
                   inc_nodes, inc_mask, labels):
        # strip the leading shard axis of size 1 that shard_map hands us
        feats, kind, nmask = feats[0], kind[0], nmask[0]
        esrc, edst_local, emask = esrc[0], edst_local[0], emask[0]
        inc_nodes, inc_mask, labels = inc_nodes[0], inc_mask[0], labels[0]

        # local degree of local dst nodes
        nps = feats.shape[0]
        deg = jnp.zeros(nps, feats.dtype).at[edst_local].add(emask)
        inv_deg = jnp.where(deg > 0, 1.0 / jnp.maximum(deg, 1.0), 0.0)

        h_local = jax.nn.relu(
            feats @ params["embed_w"] + params["embed_b"] + params["kind_emb"][kind]
        ) * nmask[:, None]

        for layer in params["layers"]:
            # halo exchange: every shard needs src embeddings of its in-edges
            h_full = jax.lax.all_gather(h_local, "graph", tiled=True)   # [N, H]
            msg = h_full[esrc] * emask[:, None]
            agg = jnp.zeros_like(h_local).at[edst_local].add(msg) * inv_deg[:, None]
            h_local = jax.nn.relu(
                h_local @ layer["w_self"] + agg @ layer["w_msg"] + layer["b"]
            ) + h_local

        h_full = jax.lax.all_gather(h_local, "graph", tiled=True)
        logits = h_full[inc_nodes] @ params["head_w"] + params["head_b"]   # [B/D, C]
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
        # incidents are dp-sharded; graph shards all compute the same readout
        loss_sum = jax.lax.psum((nll * inc_mask).sum(), "dp")
        count = jax.lax.psum(inc_mask.sum(), "dp")
        return (loss_sum / jnp.maximum(count, 1.0))[None]

    return shard_map(
        local_loss,
        mesh=mesh,
        in_specs=(
            P(),                      # params replicated
            P("graph"), P("graph"), P("graph"),          # nodes
            P("graph"), P("graph"), P("graph"),          # edges
            P("dp"), P("dp"), P("dp"),                   # incidents
        ),
        out_specs=P("graph"),  # per-graph-shard copy of the scalar loss
        check_vma=False,
    )


def make_sharded_train_step(mesh: Mesh, tx):
    """jitted (params, opt_state, part: PartitionedGraph arrays) -> step."""
    sharded_loss = _sharded_loss(mesh)

    def loss_scalar(params, *arrs):
        return sharded_loss(params, *arrs).mean()

    @jax.jit
    def step(params, opt_state, feats, kind, nmask, esrc, edst, emask,
             inc_nodes, inc_mask, labels):
        loss, grads = jax.value_and_grad(loss_scalar)(
            params, feats, kind, nmask, esrc, edst, emask,
            inc_nodes, inc_mask, labels)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    return step


def device_put_partitioned(part, mesh: Mesh) -> tuple:
    """Place PartitionedGraph arrays with their mesh shardings."""
    g = NamedSharding(mesh, P("graph"))
    d = NamedSharding(mesh, P("dp"))
    put = jax.device_put
    return (
        put(part.features, g), put(part.node_kind, g), put(part.node_mask, g),
        put(part.edge_src, g), put(part.edge_dst_local, g), put(part.edge_mask, g),
        put(part.incident_nodes, d), put(part.incident_mask, d), put(part.labels, d),
    )
