from .mesh import dp_sharded, graph_sharded, make_mesh, replicated
from .multihost import host_local_incident_slice, init_distributed, make_multihost_mesh
from .partition import PartitionedGraph, partition_snapshot
from .sharded_gnn import device_put_partitioned, make_sharded_train_step
from .sharded_rules import (
    ShardedBatch, device_put_graph_sharded, device_put_sharded_batch,
    make_graph_sharded_score, make_sharded_score, shard_batch,
)

__all__ = [
    "make_mesh", "replicated", "dp_sharded", "graph_sharded",
    "PartitionedGraph", "partition_snapshot",
    "make_sharded_train_step", "device_put_partitioned",
    "init_distributed", "make_multihost_mesh", "host_local_incident_slice",
    "ShardedBatch", "shard_batch", "make_sharded_score",
    "device_put_sharded_batch", "make_graph_sharded_score",
    "device_put_graph_sharded",
]
