"""Host-side graph partitioning for the (dp × graph) mesh.

Nodes are split into contiguous ranges, one per ``graph`` shard; edges are
assigned to the shard that owns their *destination* (so the scatter-add of
incoming messages is shard-local and only source embeddings cross shards
via all-gather — the halo exchange). Incidents are round-robined over
``dp`` shards. All per-shard arrays are padded to a common static size so
the shard_map'd step compiles once.

Per-shard edges carry the same relation-bucketed layout as the snapshot
(graph/snapshot.py): each shard's edges are sorted by (rel, dst_local)
into per-relation slices whose capacities are shared across shards (max
over shards, padded to the REL_SLICE_BUCKETS ladder) — one static
``rel_offsets`` tuple therefore describes EVERY shard, which is what lets
the shard_map'd bucketed kernel compile once.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..analysis.ladders import INCIDENT_BUCKET_SIZES
from ..graph.schema import RelationKind
from ..graph.snapshot import GraphSnapshot, rel_slice_offsets
from ..utils.padding import bucket_for


@dataclass(frozen=True)
class PartitionedGraph:
    """Stacked per-shard arrays; leading axes are mesh axes."""
    # graph axis: nodes split into G contiguous ranges of size Pn/G
    features: np.ndarray        # [G, Pn/G, DIM]
    node_kind: np.ndarray       # [G, Pn/G]
    node_mask: np.ndarray       # [G, Pn/G]
    # graph axis: edges grouped by dst shard, dst made shard-local,
    # relation-bucketed per shard (shared static rel_offsets)
    edge_src: np.ndarray        # [G, Pe_shard] global src index
    edge_dst_local: np.ndarray  # [G, Pe_shard] dst - shard*Pn/G
    edge_rel: np.ndarray        # [G, Pe_shard] RelationKind (-1 = padding)
    edge_mask: np.ndarray       # [G, Pe_shard]
    # dp axis: incidents round-robined
    incident_nodes: np.ndarray  # [D, Pi/D] global node index
    incident_mask: np.ndarray   # [D, Pi/D]
    labels: np.ndarray          # [D, Pi/D]
    nodes_per_shard: int
    rel_offsets: tuple[int, ...] = ()   # [R+1] shared per-shard slices


def partition_snapshot(
    snapshot: GraphSnapshot,
    dp: int,
    graph: int,
    labels: np.ndarray | None = None,
) -> PartitionedGraph:
    pn = snapshot.padded_nodes
    if pn % graph:
        raise ValueError(f"padded nodes {pn} not divisible by graph={graph}")
    nps = pn // graph

    features = snapshot.features.reshape(graph, nps, -1)
    node_kind = snapshot.node_kind.reshape(graph, nps)
    node_mask = snapshot.node_mask.reshape(graph, nps)

    live = snapshot.edge_mask > 0
    src = snapshot.edge_src[live]
    dst = snapshot.edge_dst[live]
    rel = snapshot.edge_rel[live]
    owner = dst // nps
    num_rels = len(RelationKind)
    # shared per-relation capacities: the max count over shards, bucketed
    counts = np.zeros((graph, num_rels), np.int64)
    for g in range(graph):
        sel = owner == g
        if sel.any():
            counts[g] = np.bincount(rel[sel], minlength=num_rels)
    rel_offsets = rel_slice_offsets(counts.max(axis=0) if len(src) else
                                    np.zeros(num_rels, np.int64))
    pe_shard = max(int(rel_offsets[-1]), 1)

    e_src = np.zeros((graph, pe_shard), np.int32)
    # padding dst_local = LAST local row: keeps each slice non-decreasing
    # in dst through its padded tail (mask-zeroed adds either way)
    e_dst = np.full((graph, pe_shard), nps - 1, np.int32)
    e_rel = np.full((graph, pe_shard), -1, np.int32)
    e_mask = np.zeros((graph, pe_shard), np.float32)
    for g in range(graph):
        sel = owner == g
        gs, gd, gr = src[sel], dst[sel] - g * nps, rel[sel]
        order = np.lexsort((gd, gr))       # rel major, dst_local minor
        gs, gd, gr = gs[order], gd[order], gr[order]
        pos = 0
        for r in range(num_rels):
            c = int(counts[g, r])
            lo = rel_offsets[r]
            e_src[g, lo:lo + c] = gs[pos:pos + c]
            e_dst[g, lo:lo + c] = gd[pos:pos + c]
            e_rel[g, lo:lo + c] = gr[pos:pos + c]
            e_mask[g, lo:lo + c] = 1.0
            pos += c

    pi = snapshot.padded_incidents
    per_dp = -(-pi // dp)
    per_dp = bucket_for(per_dp, INCIDENT_BUCKET_SIZES)
    inc_nodes = np.zeros((dp, per_dp), np.int32)
    inc_mask = np.zeros((dp, per_dp), np.float32)
    lab = np.zeros((dp, per_dp), np.int32)
    full_labels = (np.asarray(labels, dtype=np.int32) if labels is not None
                   else np.zeros(pi, np.int32))
    for i in range(snapshot.num_incidents):
        d, slot = i % dp, i // dp
        inc_nodes[d, slot] = snapshot.incident_nodes[i]
        inc_mask[d, slot] = snapshot.incident_mask[i]
        if i < len(full_labels):
            lab[d, slot] = full_labels[i]

    return PartitionedGraph(
        features=features, node_kind=node_kind, node_mask=node_mask,
        edge_src=e_src, edge_dst_local=e_dst, edge_rel=e_rel,
        edge_mask=e_mask,
        incident_nodes=inc_nodes, incident_mask=inc_mask, labels=lab,
        nodes_per_shard=nps,
        rel_offsets=rel_offsets,
    )
