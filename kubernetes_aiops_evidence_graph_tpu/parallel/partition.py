"""Host-side graph partitioning for the (dp × graph) mesh.

Nodes are split into contiguous ranges, one per ``graph`` shard; edges are
assigned to the shard that owns their *destination* (so the scatter-add of
incoming messages is shard-local and only source embeddings cross shards
via all-gather — the halo exchange). Incidents are round-robined over
``dp`` shards. All per-shard arrays are padded to a common static size so
the shard_map'd step compiles once.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..graph.snapshot import GraphSnapshot
from ..utils.padding import bucket_for


@dataclass(frozen=True)
class PartitionedGraph:
    """Stacked per-shard arrays; leading axes are mesh axes."""
    # graph axis: nodes split into G contiguous ranges of size Pn/G
    features: np.ndarray        # [G, Pn/G, DIM]
    node_kind: np.ndarray       # [G, Pn/G]
    node_mask: np.ndarray       # [G, Pn/G]
    # graph axis: edges grouped by dst shard, dst made shard-local
    edge_src: np.ndarray        # [G, Pe_shard] global src index
    edge_dst_local: np.ndarray  # [G, Pe_shard] dst - shard*Pn/G
    edge_rel: np.ndarray        # [G, Pe_shard] RelationKind (-1 = padding)
    edge_mask: np.ndarray       # [G, Pe_shard]
    # dp axis: incidents round-robined
    incident_nodes: np.ndarray  # [D, Pi/D] global node index
    incident_mask: np.ndarray   # [D, Pi/D]
    labels: np.ndarray          # [D, Pi/D]
    nodes_per_shard: int


def partition_snapshot(
    snapshot: GraphSnapshot,
    dp: int,
    graph: int,
    labels: np.ndarray | None = None,
) -> PartitionedGraph:
    pn = snapshot.padded_nodes
    if pn % graph:
        raise ValueError(f"padded nodes {pn} not divisible by graph={graph}")
    nps = pn // graph

    features = snapshot.features.reshape(graph, nps, -1)
    node_kind = snapshot.node_kind.reshape(graph, nps)
    node_mask = snapshot.node_mask.reshape(graph, nps)

    live = snapshot.edge_mask > 0
    src = snapshot.edge_src[live]
    dst = snapshot.edge_dst[live]
    rel = snapshot.edge_rel[live]
    owner = dst // nps
    counts = np.bincount(owner, minlength=graph)
    pe_shard = bucket_for(max(int(counts.max()) if counts.size else 1, 1),
                          (256, 1024, 4096, 16384, 65536, 262144))

    e_src = np.zeros((graph, pe_shard), np.int32)
    e_dst = np.zeros((graph, pe_shard), np.int32)
    e_rel = np.full((graph, pe_shard), -1, np.int32)
    e_mask = np.zeros((graph, pe_shard), np.float32)
    for g in range(graph):
        sel = owner == g
        k = int(sel.sum())
        e_src[g, :k] = src[sel]
        e_dst[g, :k] = dst[sel] - g * nps
        e_rel[g, :k] = rel[sel]
        e_mask[g, :k] = 1.0

    pi = snapshot.padded_incidents
    per_dp = -(-pi // dp)
    per_dp = bucket_for(per_dp, (8, 32, 128, 512))
    inc_nodes = np.zeros((dp, per_dp), np.int32)
    inc_mask = np.zeros((dp, per_dp), np.float32)
    lab = np.zeros((dp, per_dp), np.int32)
    full_labels = (np.asarray(labels, dtype=np.int32) if labels is not None
                   else np.zeros(pi, np.int32))
    for i in range(snapshot.num_incidents):
        d, slot = i % dp, i // dp
        inc_nodes[d, slot] = snapshot.incident_nodes[i]
        inc_mask[d, slot] = snapshot.incident_mask[i]
        if i < len(full_labels):
            lab[d, slot] = full_labels[i]

    return PartitionedGraph(
        features=features, node_kind=node_kind, node_mask=node_mask,
        edge_src=e_src, edge_dst_local=e_dst, edge_rel=e_rel,
        edge_mask=e_mask,
        incident_nodes=inc_nodes, incident_mask=inc_mask, labels=lab,
        nodes_per_shard=nps,
    )
