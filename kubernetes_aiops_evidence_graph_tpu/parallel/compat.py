"""jax API compatibility seam for ``shard_map``.

Newer jax exports ``shard_map`` at top level with a ``check_vma`` kwarg;
the 0.4.x line ships it under ``jax.experimental.shard_map`` with the same
semantics spelled ``check_rep``. Every shard_map call in the repo goes
through this one wrapper so the rest of the code can use the current
spelling regardless of the installed jax.
"""
from __future__ import annotations

try:                                 # jax >= 0.6: top-level, check_vma
    from jax import shard_map as _shard_map
    _REP_KW = "check_vma"
except ImportError:                  # jax 0.4.x: experimental, check_rep
    from jax.experimental.shard_map import shard_map as _shard_map
    _REP_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = False):
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **{_REP_KW: check_vma})
