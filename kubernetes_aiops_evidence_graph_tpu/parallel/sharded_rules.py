"""Multi-chip rules scoring — the batched RCA pass sharded over ``dp``.

Incident scoring is embarrassingly parallel across incidents (each row of
the dense evidence table folds independently — rca/tpu_backend.py), so the
scale-out story is pure data parallelism: the host splits the DeviceBatch's
incident rows into D contiguous blocks, node features stay replicated (every
shard gathers arbitrary global node indices), and a shard_map over the
``dp`` axis runs the identical per-shard scoring kernel with zero
cross-shard collectives in the forward pass. ICI carries only the one-time
feature broadcast. This is how one slice scores millions of open incidents:
throughput scales linearly in D while the per-shard pass keeps the
single-chip shape the compiler already knows.

All batch arrays are row-aligned ([Pi, ...]), including the per-slot pair
ids for multiple_pods_same_node, so sharding is a pure reshape.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from .compat import shard_map

from ..rca.tpu_backend import DeviceBatch, _score_device


@dataclass(frozen=True)
class ShardedBatch:
    """DeviceBatch split into D stacked incident-row blocks."""
    num_shards: int
    rows_per_shard: int          # Pi/D
    num_incidents: int
    pair_width: int
    ev_idx: np.ndarray           # [D, Pi/D, W]
    ev_cnt: np.ndarray           # [D, Pi/D]
    ev_pair_slot: np.ndarray     # [D, Pi/D, W]
    features: np.ndarray         # [Pn, DIM] replicated


def shard_batch(batch: DeviceBatch, dp: int) -> ShardedBatch:
    """Split a prepared DeviceBatch into ``dp`` contiguous row blocks."""
    pi = batch.padded_incidents
    if pi % dp:
        raise ValueError(f"padded incidents {pi} not divisible by dp={dp}")
    rows = pi // dp
    return ShardedBatch(
        num_shards=dp, rows_per_shard=rows, num_incidents=batch.num_incidents,
        pair_width=batch.pair_width,
        ev_idx=batch.ev_idx.reshape(dp, rows, -1).astype(np.int32),
        ev_cnt=batch.ev_cnt.reshape(dp, rows).astype(np.int32),
        ev_pair_slot=batch.ev_pair_slot.reshape(dp, rows, -1).astype(np.int32),
        features=batch.features,
    )


def make_sharded_score(mesh: Mesh, rows_per_shard: int, pair_width: int):
    """shard_map'd scoring pass over the mesh's ``dp`` axis.

    Returns a jitted fn(features, ev_idx, ev_cnt, ev_pair_slot). Each shard
    emits its [Pi/D, ...] block and shard_map concatenates them back to
    global [Pi, ...] outputs (conds, matched, scores, top_idx, any_match,
    top_conf, top_score) in original row order (rows split contiguously)."""

    def local_score(features, ev_idx, ev_cnt, ev_pair_slot):
        zero = jnp.zeros((rows_per_shard,), jnp.float32)
        return _score_device.__wrapped__(
            features, ev_idx[0], ev_cnt[0], ev_pair_slot[0], zero,
            padded_incidents=rows_per_shard, pair_width=pair_width)

    dp_spec = P("dp")
    sharded = shard_map(
        local_score,
        mesh=mesh,
        in_specs=(P(), dp_spec, dp_spec, dp_spec),  # features replicated
        out_specs=tuple([dp_spec] * 7),
        check_vma=False,
    )
    return jax.jit(sharded)


def device_put_sharded_batch(sb: ShardedBatch, mesh: Mesh) -> tuple:
    """Place arrays: features replicated, everything else dp-sharded."""
    rep = NamedSharding(mesh, P())
    dp = NamedSharding(mesh, P("dp"))
    return (
        jax.device_put(sb.features, rep),
        jax.device_put(sb.ev_idx, dp), jax.device_put(sb.ev_cnt, dp),
        jax.device_put(sb.ev_pair_slot, dp),
    )


# -- graph-sharded variant: node features split over the 'graph' axis ------
#
# When the feature matrix outgrows one chip's HBM (millions of nodes), the
# dp-replicated layout above stops working. Here features are sharded into G
# contiguous node blocks over the 'graph' mesh axis and the evidence fold
# becomes a RING: each of the G steps holds one remote feature block
# (ppermute over 'graph', the ring-attention pattern of sharded_gnn), folds
# the evidence slots whose global node id lives in that block, and rotates.
# Per-shard memory is O(Pn/G · DIM); every (dp, graph) shard sees every
# block once, so after G steps counts are complete and the shared
# finish_scores tail runs unchanged. Compute is replicated across the graph
# axis (the fold is cheap — the axis exists for capacity, not FLOPs).

from .sharded_gnn import _ring_perm  # noqa: E402 — shared ring permutation


def evidence_fold_block(h_blk, ev_idx, ev_cnt, ev_pair_slot, lo, *,
                        nodes_per_shard: int, pair_width: int,
                        rows_per_shard: int):
    """Chunked fold of the evidence slots whose GLOBAL node id lives in
    ``[lo, lo + nodes_per_shard)`` of node block ``h_blk``: bounds the
    [rows, chunk, DIM] intermediate exactly like the single-device
    _aggregate; the pair one-hot contraction rides the same in-block
    gathered rows. Out-of-block slots contribute exact zeros, so folding
    every block once — in any grouping — reproduces the single-device
    fold bit-exactly. Shared by the batch ring fold below and the
    owner-fold of the graph-sharded streaming tick
    (parallel/sharded_streaming.py)."""
    from ..graph.schema import F
    from ..rca.tpu_backend import _FOLD_CHUNK, pair_contract

    slot_live = (jax.lax.broadcasted_iota(jnp.int32, ev_idx.shape, 1)
                 < ev_cnt[:, None]).astype(h_blk.dtype)       # [rows, W]
    width = ev_idx.shape[1]

    def fold_slice(idx, pslot, live):
        in_blk = ((idx >= lo) & (idx < lo + nodes_per_shard)
                  ).astype(h_blk.dtype) * live
        local = jnp.clip(idx - lo, 0, nodes_per_shard - 1)
        rows = h_blk[local] * in_blk[:, :, None]
        return (rows.sum(axis=1),
                pair_contract(rows[:, :, F.POD_PROBLEM], pslot,
                              pair_width))

    if width <= _FOLD_CHUNK:
        return fold_slice(ev_idx, ev_pair_slot, slot_live)

    def chunk_body(acc, i):
        sl_i = jax.lax.dynamic_slice_in_dim(
            ev_idx, i * _FOLD_CHUNK, _FOLD_CHUNK, axis=1)
        sl_p = jax.lax.dynamic_slice_in_dim(
            ev_pair_slot, i * _FOLD_CHUNK, _FOLD_CHUNK, axis=1)
        sl_m = jax.lax.dynamic_slice_in_dim(
            slot_live, i * _FOLD_CHUNK, _FOLD_CHUNK, axis=1)
        c, pc = fold_slice(sl_i, sl_p, sl_m)
        return (acc[0] + c, acc[1] + pc), None
    (c, pc), _ = jax.lax.scan(
        chunk_body,
        (jnp.zeros((rows_per_shard, h_blk.shape[1]), jnp.float32),
         jnp.zeros((rows_per_shard, pair_width), jnp.float32)),
        jnp.arange(width // _FOLD_CHUNK))
    return c, pc


def ring_fold(blk, ev_idx, ev_cnt, ev_pair_slot, *, nodes_per_shard: int,
              g_size: int, pair_width: int, rows_per_shard: int):
    """Ring evidence fold over 'graph'-sharded node features.

    Must run inside a shard_map whose mesh has a ``graph`` axis. ``blk`` is
    this shard's [Pn/G, DIM] node block; the evidence tables are this
    shard's local [rows, W] views. Each of the G steps folds the slots
    whose GLOBAL node id lives in the currently-held block
    (evidence_fold_block), then rotates the block one hop (ppermute — the
    ring-attention pattern of sharded_gnn). Returns ([rows, DIM] counts,
    [rows, pair_width] pair_counts): complete after all G rotations. Used
    by the batch graph-sharded pass (make_graph_sharded_score); the
    streaming tick uses the cheaper owner-fold + psum
    (parallel/sharded_streaming.py)."""

    def _fold_block(h_blk, lo):
        return evidence_fold_block(
            h_blk, ev_idx, ev_cnt, ev_pair_slot, lo,
            nodes_per_shard=nodes_per_shard, pair_width=pair_width,
            rows_per_shard=rows_per_shard)

    my = jax.lax.axis_index("graph")

    def body(r, carry):
        h_blk, counts, pair_counts = carry
        src_shard = jnp.mod(my - r, g_size)
        lo = src_shard * nodes_per_shard
        c, pc = _fold_block(h_blk, lo)
        h_blk = jax.lax.ppermute(h_blk, "graph", _ring_perm(g_size))
        return h_blk, counts + c, pair_counts + pc

    _, counts, pair_counts = jax.lax.fori_loop(
        0, g_size, body,
        (blk,
         jnp.zeros((rows_per_shard, blk.shape[1]), jnp.float32),
         jnp.zeros((rows_per_shard, pair_width), jnp.float32)))
    return counts, pair_counts


def make_graph_sharded_score(mesh: Mesh, rows_per_shard: int,
                             nodes_per_shard: int, pair_width: int):
    """shard_map'd scoring over a (dp × graph) mesh with sharded features.

    fn(features_blocks [G, Pn/G, DIM], ev_idx, ev_cnt, ev_pair_slot) ->
    global [Pi, ...] outputs."""
    from ..rca.tpu_backend import finish_scores

    g_size = mesh.shape["graph"]

    def local_score(features, ev_idx, ev_cnt, ev_pair_slot):
        counts, pair_counts = ring_fold(
            features[0], ev_idx[0], ev_cnt[0], ev_pair_slot[0],
            nodes_per_shard=nodes_per_shard, g_size=g_size,
            pair_width=pair_width, rows_per_shard=rows_per_shard)
        per_row_max = pair_counts.max(axis=1)
        return finish_scores(counts, per_row_max, rows_per_shard)

    dp_spec = P("dp")
    sharded = shard_map(
        local_score,
        mesh=mesh,
        in_specs=(P("graph"), dp_spec, dp_spec, dp_spec),
        out_specs=tuple([dp_spec] * 7),
        check_vma=False,
    )
    return jax.jit(sharded)


def device_put_graph_sharded(sb: ShardedBatch, mesh: Mesh,
                             graph: int) -> tuple:
    """Place arrays for the graph-sharded pass: features split into
    ``graph`` contiguous node blocks, everything else dp-sharded."""
    pn = sb.features.shape[0]
    if pn % graph:
        raise ValueError(f"padded nodes {pn} not divisible by graph={graph}")
    blocks = sb.features.reshape(graph, pn // graph, -1)
    gsh = NamedSharding(mesh, P("graph"))
    dp = NamedSharding(mesh, P("dp"))
    return (
        jax.device_put(blocks, gsh),
        jax.device_put(sb.ev_idx, dp), jax.device_put(sb.ev_cnt, dp),
        jax.device_put(sb.ev_pair_slot, dp),
    )
