"""Multi-chip rules scoring — the batched RCA pass sharded over ``dp``.

Incident scoring is embarrassingly parallel across incidents (each row of
the dense evidence table folds independently — rca/tpu_backend.py), so the
scale-out story is pure data parallelism: the host splits the DeviceBatch's
incident rows into D contiguous blocks, node features stay replicated (every
shard gathers arbitrary global node indices), and a shard_map over the
``dp`` axis runs the identical per-shard scoring kernel with zero
cross-shard collectives in the forward pass. ICI carries only the one-time
feature broadcast. This is how one slice scores millions of open incidents:
throughput scales linearly in D while the per-shard pass keeps the
single-chip shape the compiler already knows.

The pair tables (multiple_pods_same_node condition) are partitioned by
incident row on the host, so the per-(incident, node) compaction stays
shard-local too.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from ..rca.tpu_backend import DeviceBatch, _score_device
from ..utils.padding import bucket_for

_PAIR_BUCKETS = (64, 256, 1024, 4096, 16384, 65536)


@dataclass(frozen=True)
class ShardedBatch:
    """DeviceBatch split into D stacked incident-row blocks."""
    num_shards: int
    rows_per_shard: int          # Pi/D
    num_incidents: int
    ev_idx: np.ndarray           # [D, Pi/D, W]
    ev_cnt: np.ndarray           # [D, Pi/D]
    pair_ids: np.ndarray         # [D, Pc']
    pair_pod: np.ndarray         # [D, Pc']
    pair_mask: np.ndarray        # [D, Pc']
    pair_rows: np.ndarray        # [D, Pp'] — shard-local incident row
    pair_rows_mask: np.ndarray   # [D, Pp']
    features: np.ndarray         # [Pn, DIM] replicated


def shard_batch(batch: DeviceBatch, dp: int) -> ShardedBatch:
    """Split a prepared DeviceBatch into ``dp`` contiguous row blocks."""
    pi = batch.padded_incidents
    if pi % dp:
        raise ValueError(f"padded incidents {pi} not divisible by dp={dp}")
    rows = pi // dp

    ev_idx = batch.ev_idx.reshape(dp, rows, -1)
    ev_cnt = batch.ev_cnt.reshape(dp, rows)

    # partition live pairs by the shard owning their incident row
    live_c = batch.pair_mask > 0
    live_p = batch.pair_rows_mask > 0
    pr_rows = batch.pair_rows[live_p]            # [P_live] global row per pair
    ids_live = batch.pair_ids[live_c]
    pod_live = batch.pair_pod[live_c]
    owner_p = pr_rows // rows
    # pair entries ([Pc]) reference compact pair ids; a pair's owner is the
    # owner of its incident row
    owner_c = owner_p[ids_live]

    cnt_c = np.bincount(owner_c, minlength=dp) if owner_c.size else np.zeros(dp, int)
    cnt_p = np.bincount(owner_p, minlength=dp) if owner_p.size else np.zeros(dp, int)
    pc = bucket_for(max(int(cnt_c.max()), 1), _PAIR_BUCKETS)
    pp = bucket_for(max(int(cnt_p.max()), 1), _PAIR_BUCKETS)

    pair_ids = np.full((dp, pc), pp - 1, np.int32)
    pair_pod = np.zeros((dp, pc), np.int32)
    pair_mask = np.zeros((dp, pc), np.float32)
    pair_rows = np.full((dp, pp), rows - 1, np.int32)
    pair_rows_mask = np.zeros((dp, pp), np.float32)

    for d in range(dp):
        sel_p = owner_p == d
        kp = int(sel_p.sum())
        # re-index this shard's compact pairs 0..kp-1
        old_ids = np.nonzero(sel_p)[0]
        remap = np.full(len(pr_rows) or 1, -1, np.int64)
        if kp:
            remap[old_ids] = np.arange(kp)
            pair_rows[d, :kp] = pr_rows[sel_p] - d * rows   # shard-local row
            pair_rows_mask[d, :kp] = 1.0
        sel_c = owner_c == d
        kc = int(sel_c.sum())
        if kc:
            pair_ids[d, :kc] = remap[ids_live[sel_c]]
            pair_pod[d, :kc] = pod_live[sel_c]
            pair_mask[d, :kc] = 1.0

    return ShardedBatch(
        num_shards=dp, rows_per_shard=rows, num_incidents=batch.num_incidents,
        ev_idx=ev_idx.astype(np.int32), ev_cnt=ev_cnt.astype(np.int32),
        pair_ids=pair_ids, pair_pod=pair_pod, pair_mask=pair_mask,
        pair_rows=pair_rows, pair_rows_mask=pair_rows_mask,
        features=batch.features,
    )


def make_sharded_score(mesh: Mesh, rows_per_shard: int, num_pairs: int):
    """shard_map'd scoring pass over the mesh's ``dp`` axis.

    Returns a jitted fn(features, ev_idx, ev_cnt, pair_ids, pair_pod,
    pair_mask, pair_rows, pair_rows_mask). Each shard emits its [Pi/D, ...]
    block and shard_map concatenates them back to global [Pi, ...] outputs
    (conds, matched, scores, top_idx, any_match, top_conf, top_score) in
    original row order (rows were split contiguously)."""

    def local_score(features, ev_idx, ev_cnt, pair_ids, pair_pod, pair_mask,
                    pair_rows, pair_rows_mask):
        zero = jnp.zeros((rows_per_shard,), jnp.float32)
        return _score_device.__wrapped__(
            features, ev_idx[0], ev_cnt[0], pair_ids[0], pair_pod[0],
            pair_mask[0], pair_rows[0], pair_rows_mask[0], zero,
            padded_incidents=rows_per_shard, num_pairs=num_pairs)

    dp_spec = P("dp")
    sharded = shard_map(
        local_score,
        mesh=mesh,
        in_specs=(P(),            # features replicated
                  dp_spec, dp_spec,                       # evidence table
                  dp_spec, dp_spec, dp_spec,              # pair entries
                  dp_spec, dp_spec),                      # pair rows
        out_specs=tuple([dp_spec] * 7),
        check_vma=False,
    )
    return jax.jit(sharded)


def device_put_sharded_batch(sb: ShardedBatch, mesh: Mesh) -> tuple:
    """Place arrays: features replicated, everything else dp-sharded."""
    rep = NamedSharding(mesh, P())
    dp = NamedSharding(mesh, P("dp"))
    return (
        jax.device_put(sb.features, rep),
        jax.device_put(sb.ev_idx, dp), jax.device_put(sb.ev_cnt, dp),
        jax.device_put(sb.pair_ids, dp), jax.device_put(sb.pair_pod, dp),
        jax.device_put(sb.pair_mask, dp),
        jax.device_put(sb.pair_rows, dp), jax.device_put(sb.pair_rows_mask, dp),
    )


# -- graph-sharded variant: node features split over the 'graph' axis ------
#
# When the feature matrix outgrows one chip's HBM (millions of nodes), the
# dp-replicated layout above stops working. Here features are sharded into G
# contiguous node blocks over the 'graph' mesh axis and the evidence fold
# becomes a RING: each of the G steps holds one remote feature block
# (ppermute over 'graph', the ring-attention pattern of sharded_gnn), folds
# the evidence slots whose global node id lives in that block, and rotates.
# Per-shard memory is O(Pn/G · DIM); every (dp, graph) shard sees every
# block once, so after G steps counts are complete and the shared
# finish_scores tail runs unchanged. Compute is replicated across the graph
# axis (the fold is cheap — the axis exists for capacity, not FLOPs).

from .sharded_gnn import _ring_perm  # noqa: E402 — shared ring permutation


def make_graph_sharded_score(mesh: Mesh, rows_per_shard: int, num_pairs: int,
                             nodes_per_shard: int):
    """shard_map'd scoring over a (dp × graph) mesh with sharded features.

    fn(features_blocks [G, Pn/G, DIM], ev_idx, ev_cnt, pair_ids, pair_pod,
    pair_mask, pair_rows, pair_rows_mask) -> global [Pi, ...] outputs."""
    from ..graph.schema import F
    from ..rca.tpu_backend import _FOLD_CHUNK, finish_scores

    g_size = mesh.shape["graph"]

    def local_score(features, ev_idx, ev_cnt, pair_ids, pair_pod, pair_mask,
                    pair_rows, pair_rows_mask):
        blk = features[0]                       # [Pn/G, DIM] my node block
        ev_idx_, ev_cnt_ = ev_idx[0], ev_cnt[0]
        pair_ids_, pair_pod_, pair_mask_ = pair_ids[0], pair_pod[0], pair_mask[0]
        pair_rows_, pair_rows_mask_ = pair_rows[0], pair_rows_mask[0]

        my = jax.lax.axis_index("graph")
        slot_live = (jax.lax.broadcasted_iota(jnp.int32, ev_idx_.shape, 1)
                     < ev_cnt_[:, None]).astype(blk.dtype)    # [rows, W]

        width = ev_idx_.shape[1]

        def _fold_block(h_blk, lo):
            """Chunked fold of slots whose node id lives in [lo, lo+nps):
            bounds the [rows, chunk, DIM] intermediate exactly like the
            single-device _aggregate does (tpu_backend._FOLD_CHUNK)."""
            def fold_slice(idx, live):
                in_blk = ((idx >= lo) & (idx < lo + nodes_per_shard)
                          ).astype(h_blk.dtype) * live
                local = jnp.clip(idx - lo, 0, nodes_per_shard - 1)
                return (h_blk[local] * in_blk[:, :, None]).sum(axis=1)

            if width <= _FOLD_CHUNK:
                return fold_slice(ev_idx_, slot_live)
            def chunk_body(acc, i):
                sl_i = jax.lax.dynamic_slice_in_dim(
                    ev_idx_, i * _FOLD_CHUNK, _FOLD_CHUNK, axis=1)
                sl_m = jax.lax.dynamic_slice_in_dim(
                    slot_live, i * _FOLD_CHUNK, _FOLD_CHUNK, axis=1)
                return acc + fold_slice(sl_i, sl_m), None
            out, _ = jax.lax.scan(
                chunk_body,
                jnp.zeros((rows_per_shard, h_blk.shape[1]), jnp.float32),
                jnp.arange(width // _FOLD_CHUNK))
            return out

        def body(r, carry):
            h_blk, counts, pod_prob = carry
            src_shard = jnp.mod(my - r, g_size)
            lo = src_shard * nodes_per_shard
            counts = counts + _fold_block(h_blk, lo)
            p_in = ((pair_pod_ >= lo) & (pair_pod_ < lo + nodes_per_shard)
                    ).astype(h_blk.dtype) * pair_mask_
            p_local = jnp.clip(pair_pod_ - lo, 0, nodes_per_shard - 1)
            pod_prob = pod_prob + h_blk[p_local, F.POD_PROBLEM] * p_in
            h_blk = jax.lax.ppermute(h_blk, "graph", _ring_perm(g_size))
            return h_blk, counts, pod_prob

        _, counts, pod_prob = jax.lax.fori_loop(
            0, g_size, body,
            (blk,
             jnp.zeros((rows_per_shard, blk.shape[1]), jnp.float32),
             jnp.zeros((pair_pod_.shape[0],), jnp.float32)))

        per_pair = jnp.zeros((num_pairs,), jnp.float32
                             ).at[pair_ids_].add(pod_prob)
        per_row_max = jnp.zeros((rows_per_shard,), jnp.float32
                                ).at[pair_rows_].max(per_pair * pair_rows_mask_)
        return finish_scores(counts, per_row_max, rows_per_shard)

    dp_spec = P("dp")
    sharded = shard_map(
        local_score,
        mesh=mesh,
        in_specs=(P("graph"),                   # feature blocks
                  dp_spec, dp_spec,             # evidence table
                  dp_spec, dp_spec, dp_spec,    # pair entries
                  dp_spec, dp_spec),            # pair rows
        out_specs=tuple([dp_spec] * 7),
        check_vma=False,
    )
    return jax.jit(sharded)


def device_put_graph_sharded(sb: ShardedBatch, mesh: Mesh,
                             graph: int) -> tuple:
    """Place arrays for the graph-sharded pass: features split into
    ``graph`` contiguous node blocks, everything else dp-sharded."""
    pn = sb.features.shape[0]
    if pn % graph:
        raise ValueError(f"padded nodes {pn} not divisible by graph={graph}")
    blocks = sb.features.reshape(graph, pn // graph, -1)
    gsh = NamedSharding(mesh, P("graph"))
    dp = NamedSharding(mesh, P("dp"))
    return (
        jax.device_put(blocks, gsh),
        jax.device_put(sb.ev_idx, dp), jax.device_put(sb.ev_cnt, dp),
        jax.device_put(sb.pair_ids, dp), jax.device_put(sb.pair_pod, dp),
        jax.device_put(sb.pair_mask, dp),
        jax.device_put(sb.pair_rows, dp), jax.device_put(sb.pair_rows_mask, dp),
    )
