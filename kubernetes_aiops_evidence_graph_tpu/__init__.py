"""kubernetes_aiops_evidence_graph_tpu — TPU-native Kubernetes AIOps evidence-graph platform.

A ground-up re-design of the capabilities of
``ShreyashDarade/Kubernetes-AIOps-Evidence-Graph`` (see SURVEY.md) for TPU:

* alerts are ingested, normalized and deduplicated (`ingestion/`);
* evidence is collected from cluster backends (`collectors/`) — real HTTP/K8s
  or a hermetic replayable fake driven by the simulator (`simulator/`);
* evidence is assembled into an **in-memory tensorized evidence graph**
  (`graph/`): CSR adjacency per relation type + dense node features;
* root-cause analysis runs through a plugin seam (`rca/`):
  ``cpu`` — a faithful rules-engine oracle, ``tpu`` — a batched, vectorized
  scorer (segment-sum message passing + masked rule matching) that scores
  *all* open incidents in one jitted pass (`ops/`, `parallel/`);
* a durable async workflow engine (`workflow/`) reproduces the reference's
  12-step incident lifecycle without Temporal;
* safety path: policy engine, blast radius, executor, verifier (`policy/`,
  `remediation/`), runbooks and integrations (`runbook/`, `integrations/`);
* persistence (`storage/`) and observability (`observability/`).

Import as ``import kubernetes_aiops_evidence_graph_tpu as kaeg``.
"""

__version__ = "0.1.0"

# Keep the top-level import light: jax-heavy modules are imported lazily by
# the subpackages that need them so that pure-CPU paths (models, ingestion,
# policy) never pay JAX import/compile cost.
