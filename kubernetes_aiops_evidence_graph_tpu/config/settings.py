"""Environment-driven configuration.

Capability parity with the reference settings singleton
(src/config/settings.py:11-153 in the reference): one flat, env-overridable
settings object covering app/api/storage/k8s/observability/llm/policy/
integrations/evidence/remediation knobs, plus TPU-specific knobs the
reference has no analog for (mesh shape, padding buckets, rca backend).

Implemented as a frozen dataclass built from ``os.environ`` — no
pydantic-settings dependency, import-cheap, and hashable so jitted code can
close over derived static values.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field, fields
from functools import lru_cache
from typing import Any

from ..analysis import ladders as _ladders


def _parse_buckets(raw: str) -> tuple[int, ...]:
    """Parse a bucket ladder from env: positive ints, sorted ascending."""
    vals = sorted(int(p) for p in raw.split(",") if p.strip())
    if not vals or vals[0] <= 0:
        raise ValueError(f"bucket ladder must be positive ints, got {raw!r}")
    return tuple(vals)


@dataclass(frozen=True)
class Settings:
    # --- app ---
    app_name: str = "kaeg-tpu"
    app_env: str = "development"  # development|staging|uat|production
    log_level: str = "INFO"
    debug: bool = False

    # --- api / ingestion (reference settings.py api_* / security) ---
    api_host: str = "0.0.0.0"
    api_port: int = 8000
    webhook_rate_limit_per_minute: int = 100       # settings.py:119
    dedup_ttl_seconds: int = 4 * 3600              # deduplicator.py:20 (4h)
    # graft-intake: vectorized columnar ingest — webhook batches parse
    # into NumPy columns (ingestion/columnar.py), the dedup window becomes
    # a hashed ring (ingestion/dedup.FingerprintRing) with batch probes,
    # and the scorer's pending feature deltas stage into preallocated
    # columnar buffers whose drain is a memcpy into ONE device-ready
    # int32 slab per tick (rca/streaming.FeatureStage + _delta_pack).
    # False restores the per-row dict path everywhere — the bit-parity
    # oracle (same pattern as gnn_bucketed/gnn_pallas).
    ingest_columnar: bool = True
    # hashed dedup ring capacity (slots; rounded up to a power of two).
    # Sized for ~4h of unique fingerprints at storm rates; overflowing a
    # probe neighborhood evicts the oldest-expiry entry (counted in
    # aiops_ingest_dedup_evictions_total).
    ingest_dedup_window: int = 32768
    # graft-storm: overload-robust serving. The columnar webhook path is
    # gated by a per-tenant token-bucket admission controller
    # (ingestion/admission.py) with severity-weighted shedding: when a
    # tenant's sustained inflow of dedup SURVIVORS exceeds the drain rate
    # below, rows shed lowest-severity-first (critical is NEVER shed) and
    # the response carries 429 + Retry-After derived from bucket refill.
    # Duplicates ride free — the ring absorbs them before the gate, so a
    # duplicate-heavy storm cannot shed the critical needle. False
    # restores the legacy fixed-window per-client RateLimiter alone (the
    # dict-path oracle keeps it either way).
    ingest_admission: bool = True
    # sustained per-tenant survivor drain rate (tokens/s) and burst
    # capacity of the admission bucket. The defaults are sized WAY above
    # the interactive-test envelope and at ~the measured CPU ingest
    # capacity per tenant, so steady state never sheds.
    admission_rate_per_sec: float = 2000.0
    admission_burst: float = 4000.0
    # storm mode: hysteresis-gated degraded tier. Pressure = admission
    # shed-ratio EWMA above the enter ratio, dedup-ring eviction rate or
    # absorb busy-yield rate above their thresholds; sustained pressure
    # for storm_dwell_s enters, sustained calm below the exit ratio for
    # the same dwell exits. Transitions are counted, stamped into flight
    # records, and every tick dispatched during storm carries a "storm"
    # flag in its TickSpan.
    storm_enter_shed_ratio: float = 0.25
    storm_exit_shed_ratio: float = 0.02
    storm_dwell_s: float = 1.0
    storm_eviction_rate_per_s: float = 500.0
    storm_busy_rate_per_s: float = 50.0
    # storm-mode sampled persistence: under ring-eviction pressure a
    # fresh-looking NON-critical row is overwhelmingly a re-arrival whose
    # ring entry was evicted — persist 1-in-N of them (the rest register
    # back into the ring so repeats dedup) instead of paying a DB insert
    # per re-arrival. Critical rows always persist. 0 disables sampling.
    storm_sample_every: int = 8
    # absorb() busy-yield backlog bound: a non-blocking absorb that finds
    # the serving state held normally yields (the contending boundary's
    # own sync drains the journal) — but past this many unsynced store-
    # journal records it escalates to a SYNCHRONOUS drain instead, so a
    # storm cannot grow the journal unboundedly behind a busy serving
    # loop (counted in aiops_serve_absorb_sync_drains_total).
    ingest_max_journal_backlog: int = 8192
    # circuit breakers (ingestion/admission.CircuitBreaker) around the
    # two blocking downstreams: SQLite persist (app.ingest_batch — open
    # degrades ingest to the bounded spill journal instead of timing out
    # every webhook) and device dispatch (rca/shield.py — open degrades
    # tick()/absorb() to journal-only, the store journal holds the deltas
    # until the half-open probe recovers). N consecutive failures open;
    # after the cooldown one half-open probe closes or re-opens.
    breaker_failure_threshold: int = 5
    breaker_cooldown_s: float = 2.0
    # bounded spill journal for persist-breaker-open incidents (replayed
    # on breaker close; overflow drops oldest, counted)
    persist_spill_cap: int = 4096

    # --- storage ---
    db_path: str = "kaeg.sqlite"                   # replaces Postgres DSN
    graph_persist_path: str = ""                   # optional snapshot dump dir

    # --- tracing export (reference settings.py:90-91 declares these but
    # --- never wires them; here spans actually ship) ---
    otlp_endpoint: str = ""                        # e.g. http://tempo:4318
    otel_service_name: str = "kaeg-tpu"

    # --- evidence collection (settings.py:134-136) ---
    evidence_time_window_minutes: int = 15
    max_log_lines: int = 1000
    max_metric_points: int = 500

    # --- collector backends ---
    cluster_backend: str = "fake"                  # fake|kubernetes
    prometheus_url: str = "http://localhost:9090"
    loki_url: str = "http://localhost:3100"
    kubeconfig: str = ""

    # --- rca ---
    rca_backend: str = "tpu"                       # cpu|tpu|gnn (plugin seam, BASELINE.json north star)
    rca_propagation_hops: int = 3                  # graph depth analog (neo4j.py:174 maxLevel=3)
    gnn_checkpoint: str = ""                       # orbax dir for rca_backend=gnn
    # relation-bucketed GNN message passing (gnn.py): False forces the
    # transform-then-gather reference kernel (debug/parity escape hatch)
    gnn_bucketed: bool = True
    # "" = f32 matmuls; "bfloat16" = bf16 matmul operands with f32
    # accumulation (segment-sum and residual stay f32)
    gnn_compute_dtype: str = ""
    # Pallas serving tier for the bucketed forward (ops/pallas_segment.py):
    # tiled VMEM-resident gather→matmul→accumulate kernel, bit-identical
    # to the XLA bucketed kernel. FORWARD/SERVING ONLY — training and the
    # streaming tick keep the XLA kernel (the parity oracle). Off-TPU the
    # kernel runs in interpret mode (tier-1 CPU tests exercise it so).
    gnn_pallas: bool = False
    # graft-fuse: the fused streaming tick (ops/pallas_segment.py::
    # pallas_fused_gnn_tick) — delta scatter, message pass and score
    # reduction in ONE Pallas kernel, so the [N, H] activations never
    # round-trip through HBM between stages. Bit-identical to the
    # composed scatter→kernel→score tick (the parity oracle, which stays
    # the default); f32 bucketed layouts only — every other
    # configuration silently keeps the composed tick. On the sharded
    # mirror this promotes the SHARD-LOCAL kernel to Pallas while halo
    # assembly stays in XLA. The shield's kernel-fallback rung degrades
    # fused → composed → XLA under repeated device faults.
    gnn_fused_tick: bool = False
    # graft-tide: the beyond-VMEM DMA streaming tick (ops/pallas_segment
    # .py::pallas_fused_gnn_tick_dma) — features, edge mirror and [N, H]
    # activations stay HBM-resident and stream through double-buffered
    # VMEM windows. Auto-selected by the dispatcher (when enabled) once
    # the resident tick's closed-form VMEM demand exceeds
    # vmem_budget_bytes, or whenever a quantized feature tier is on.
    # f32 path bit-identical to the composed oracle; serving-only.
    gnn_tick_dma: bool = False
    # soft VMEM budget the dispatcher compares fused_tick_vmem_bytes
    # against when picking resident vs DMA tier (the hard placement
    # ceiling is ops.pallas_segment._VMEM_HARD_LIMIT)
    vmem_budget_bytes: int = 8 * 2 ** 20
    # node rows per DMA staging block in the embed/update streams
    # (power of two; clamped to the node bucket — quantum declared in
    # analysis/ladders.py, aligned against every node rung there)
    gnn_dma_node_block: int = _ladders.DMA_NODE_BLOCK
    # quantized node-feature table for the DMA tick: "" = f32,
    # "bfloat16" = bf16 table, "int8" = per-column-scale symmetric int8
    # (quantize_features). Tolerance-gated, forces the DMA tier.
    gnn_feature_quant: str = ""
    llm_provider: str = "none"                     # none|gemini|openai|ollama
    llm_api_key: str = ""
    llm_model: str = ""

    # --- remediation / policy (settings.py remediation_*) ---
    remediation_enabled: bool = True
    remediation_dry_run: bool = True
    remediation_auto_approve_dev: bool = True
    remediation_max_blast_radius: float = 50.0
    verification_wait_seconds: int = 120           # incident_workflow.py:229
    approval_timeout_seconds: int = 4 * 3600       # incident_workflow.py:198

    # --- graft-saga: durable exactly-once remediation ---
    # two-phase action execution rides the SQLite ``action_executions``
    # ledger unconditionally (an intent row + idempotency key lands
    # BEFORE the cluster mutation, the result row after; an intent
    # without a result is in-doubt on resume and is RECONCILED by
    # probing cluster state, never blindly re-fired). These knobs cover
    # the satellite surfaces around it.
    # upper bound for scale_replicas remediation (the reference's
    # current+1 default was unbounded — a flapping workflow could walk a
    # deployment to absurd replica counts one approved action at a time)
    remediation_max_scale_replicas: int = 10
    # saga compensation: a FAILED verification rolls the action's cluster
    # effect back (scale -> restore the pre-action replica count captured
    # at execute time, cordon -> uncordon, rollback -> re-rollback;
    # restart-class actions are self-healing no-ops), policy-gated via
    # PolicyEngine.evaluate_compensation, bounded attempts, then an
    # escalate-to-human action row
    remediation_compensation: bool = True
    remediation_compensation_attempts: int = 2
    # workflow leases: run_incident_workflow acquires a fenced lease row
    # in workflow_journal before touching the incident; heartbeats extend
    # it while the run is live, and a worker that loses the lease
    # (expired + reclaimed by the resumer) is FENCED out at the next step
    # boundary instead of double-driving the workflow
    workflow_lease_enabled: bool = True
    workflow_lease_ttl_s: float = 60.0
    # resumer sweep cadence (worker.py): reclaim expired leases and
    # re-enter run_incident_workflow through the journal-replay path.
    # 0 disables the periodic loop (the startup sweep still runs).
    workflow_resume_interval_s: float = 30.0
    # resume budget per workflow (the lease token counts acquisitions):
    # past this many the workflow is left STALLED for operators instead
    # of hot-looping a deterministic failure
    workflow_max_resumes: int = 5

    # --- integrations ---
    slack_webhook_url: str = ""
    slack_channel: str = "#incidents"
    jira_url: str = ""
    jira_project: str = "OPS"
    jira_user: str = ""
    jira_token: str = ""

    # --- observability ---
    metrics_enabled: bool = True
    tracing_enabled: bool = True
    # graft-scope (observability/scope.py): per-tick serving telemetry —
    # host-boundary stage timestamps on every tick (staging / coalesce /
    # queue wait / dispatch / device completion / fetch), aggregated into
    # the webhook→verdict SLO histograms and the flight recorder. All
    # timestamping is host-side monotonic reads at the existing non-jitted
    # boundaries: the jitted ticks are untouched (COST_BASELINE invariant)
    # and the overhead contract is <1% of depth-2 steady-state throughput
    # (tests/test_scope.py, marker perf_contract).
    scope_telemetry: bool = True
    # flight recorder: ring of the last K per-tick records, dumped to
    # scope_flight_dir on every shield degradation transition or recovery
    # ("" -> .kaeg_scope/<pid>)
    scope_flight_records: int = 256
    scope_flight_dir: str = ""
    # flight-dump retention: repeated shield transitions (exactly what
    # heal-ladder chaos produces) would otherwise grow the dump dir
    # without bound — keep only the newest K dumps per directory, prune
    # older ones (counted in aiops_scope_flight_dumps_pruned_total).
    # 0 disables pruning.
    flight_dump_keep: int = 64

    # --- TPU-native knobs (new in this framework) ---
    # pipelined serving executor (rca/streaming.py): max ticks in flight
    # (dispatched but unfetched). Depth 1 = the old serialized
    # dispatch→fetch loop; depth 2 (default) overlaps host delta-packing
    # of tick t+1 with device execution of tick t. When the queue is
    # full, pending deltas coalesce into one larger tick (bounded by the
    # _DELTA_BUCKETS retrace ladder) instead of blocking or queueing
    # unboundedly. Results are bit-identical at every depth.
    serve_pipeline_depth: int = 2
    # graft-fleet (parallel/sharded_streaming.py): shard the RESIDENT
    # streaming serving state over a ``graph`` mesh axis of this many
    # devices. 1 (default) = exact current single-device behavior. > 1:
    # the scorer builds a (1 x D) mesh, node/feature/evidence tables and
    # the GNN edge mirror split into D contiguous graph partitions, the
    # host delta-packing stage routes each delta batch to its owner shard
    # (per-shard _DELTA_BUCKETS sub-buckets), and each tick runs the
    # ring-halo message pass — exactly (LAYERS+1)*D ppermutes of
    # [N/D, H] blocks, zero [N, H] all-gathers (CostSpec-pinned in
    # analysis/registry.py). On CPU hosts the virtual-device fallback
    # (parallel/mesh.ensure_host_devices) makes this testable hermetically.
    serve_graph_shards: int = 1
    # workflow verdict fetch narrowing (tpu_backend.score_snapshot): "top"
    # (default) fetches only the per-incident verdict fields — the wide
    # [Pi, C]/[Pi, R] conditions/matched/scores tables never leave the
    # device on the snapshot-scoring verdict path. "full" restores the
    # wide fetch (every matched rule becomes a ranked Hypothesis).
    workflow_verdict_fields: str = "top"
    # graft-shield (rca/shield.py): crash-consistent recovery + graceful
    # degradation over the donated serving state. When enabled, the
    # workflow worker wraps the resident scorer in a ShieldedScorer: every
    # applied delta batch is write-ahead journaled (fsync, O(delta)) and
    # the resident state snapshots every `shield_snapshot_every_ticks`
    # generation boundaries, so any single failure recovers via
    # snapshot + journal-suffix replay — bit-identical and strictly
    # cheaper than a full rebuild.
    shield_enabled: bool = False
    shield_dir: str = ""                       # "" -> .kaeg_shield/<pid>
    # snapshot cadence: each snapshot is O(resident state) (one packed
    # device fetch + host-state pickle), so it amortizes over the cadence;
    # recovery replays at most this many ticks of journal suffix. At the
    # serving target (~10 ticks/s) 512 ≈ one snapshot per minute.
    shield_snapshot_every_ticks: int = 512
    # WAL group commit: every delta batch is written+flushed before it is
    # applied; the fsync may be deferred up to this many batches (1 =
    # strict). Only whole-host crashes can lose the unsynced tail — the
    # donated-state fault model keeps the host (and the page cache) alive.
    shield_wal_fsync_every_ticks: int = 8
    # watchdog: a tick exceeding this wall time counts a trip and degrades
    # the pipeline to synchronous depth 1 (XLA dispatches cannot be
    # cancelled host-side; the watchdog bounds *recurrence*, not the tick)
    shield_tick_timeout_s: float = 30.0
    # bounded retry for transient faults: exponential backoff with
    # deterministic seeded jitter (workflow/engine.RetryPolicy semantics)
    shield_retry_attempts: int = 2
    shield_retry_backoff_s: float = 0.05
    # graft-heal (rca/heal.py): elastic shard-loss survival for the
    # graph-sharded resident serving state. A shard-localized fault feeds
    # a per-mesh-position CircuitBreaker; mesh_shard_failure_threshold
    # CONSECUTIVE failures on one position classify it persistently
    # failed, and the shield's mesh_heal ladder rung (between journal
    # replay and full rebuild) re-places the resident state onto a
    # survivor mesh at the largest viable D' < D — rules verdicts
    # bit-identical to a fresh D' build, GNN verdict-identical (the
    # graft-fleet contract). After mesh_heal_cooldown_s the dead device's
    # breaker admits its half-open probe and the mesh re-expands D'→D at
    # a queue generation boundary. Both directions are WAL-journaled
    # (crash-mid-heal recovers to a consistent shard count).
    mesh_heal_enabled: bool = True
    mesh_shard_failure_threshold: int = 3
    mesh_heal_cooldown_s: float = 5.0
    # per-shard state attestation at snapshot generation boundaries: a
    # jitted checksum fold of the node-addressed resident arrays vs the
    # host-truth mirrors localizes SILENT per-shard corruption to the one
    # shard that must heal (repaired in place from host truth — never a
    # whole-state rebuild) instead of waiting for the nonfinite backstop
    # to catch a wrong verdict.
    mesh_attest: bool = True
    # graft-swell (rca/elastic.py + multi-pack SurgeServer): load-driven
    # elastic meshes.  The ElasticController consumes gauges graft-scope
    # already exports (roofline achieved-bytes/s vs modeled ceiling,
    # pipeline queue depth / stall seconds, admission shed-ratio EWMA) and
    # drives hysteresis+dwell-gated D->D' scale decisions through the
    # SAME WAL-journaled adopt_mesh seam graft-heal uses, so a scale
    # event pays an upload, never a compile, and keeps bit-parity.
    elastic_enabled: bool = False
    # both directions must hold for dwell_s before a scale fires (the
    # StormMode hysteresis pattern — no flapping on a transient spike).
    elastic_dwell_s: float = 10.0
    # scale UP when pipeline occupancy (inflight/depth) or shed EWMA
    # exceeds these, or roofline achieved-bytes/s exceeds this fraction
    # of the modeled ceiling; scale DOWN when all fall below the lows.
    elastic_up_occupancy: float = 0.75
    elastic_down_occupancy: float = 0.25
    elastic_up_shed: float = 0.05
    elastic_down_shed: float = 0.005
    elastic_up_roofline: float = 0.85
    elastic_down_roofline: float = 0.30
    # cooldown between consecutive scale events (seconds).
    elastic_cooldown_s: float = 30.0
    # fleet bin-packing: max tenants per MultiTenantScorer pack and max
    # packs.  swell_max_packs=1 preserves the single-pack PR-9 behavior.
    swell_pack_tenants: int = 4
    swell_max_packs: int = 1
    # per-tenant admitted-rows/s load estimate smoothing.
    swell_load_alpha: float = 0.2
    # fleet-WAL path for placement/migration records; empty = in-memory
    # (single-process: placement is trivially re-derivable at boot)
    swell_journal_path: str = ""
    # graft-evolve (learn/): the online learning loop — production
    # verdicts (verification outcomes, operator HypothesisFeedback,
    # rule-confirmed verdicts) harvested into labeled episodes, a
    # background fine-tune from the live checkpoint, an eval GATE
    # (candidate holdout top-1 must be >= the serving checkpoint's or it
    # is discarded, counted in aiops_learn_gate_rejects_total), and a hot
    # checkpoint swap into the serving executors at a generation boundary
    # of the double-buffered queue (in-flight ticks complete on old
    # params; same shapes => no retrace). Swaps are journaled through the
    # shield WAL when the scorer is shielded, so crash recovery replays
    # onto the correct params generation.
    learn_enabled: bool = False
    learn_interval_s: float = 30.0       # background loop cadence
    learn_steps: int = 120               # fine-tune steps per cycle
    learn_lr: float = 1e-3
    # proximal anchor: fine-tune loss carries 0.5*w*||theta - serving||^2
    # pulling the candidate toward the live checkpoint — the parameter-
    # space half of the anti-forgetting story (the replay mix is the
    # data-space half)
    learn_anchor_weight: float = 1e-3
    learn_min_episodes: int = 2          # buffer floor before training
    learn_buffer_cap: int = 64           # dedup'd replay buffer episodes
    # simulator episodes mixed into every fine-tune (anti-forgetting) and
    # the simulator holdout suite the gate evaluates against
    learn_sim_episodes: int = 4
    learn_sim_holdout: int = 2
    learn_sim_pods: int = 96
    learn_sim_incidents: int = 6
    # every Nth harvested production episode is HELD OUT of training and
    # joins the gate's production holdout slice instead
    learn_holdout_every: int = 4
    # label fallback: rule-confirmed verdicts (rules-backend top-1 at
    # confidence >= learn_weak_confidence) label incidents that never got
    # operator feedback or a verification outcome
    learn_weak_labels: bool = True
    learn_weak_confidence: float = 0.9
    learn_checkpoint_dir: str = ""       # "" -> .kaeg_learn/<pid>
    # >1: the fine-tune drives the existing sharded train step
    # (parallel/sharded_gnn.make_sharded_train_step) on a (1 x D) data
    # mesh — forced host devices on CPU, same fallback as serving
    learn_mesh_shards: int = 1
    # graft-fuse: run the online fine-tune through the Pallas vjp tier
    # (ops/pallas_segment.py custom_vjp — forward AND backward as Pallas
    # kernels). Guarded by a gate-time parity check: the first cycle
    # compares one step's loss+grads against the XLA step and silently
    # falls back to XLA on mismatch, so a lowering bug can never reach a
    # hot swap (learn/trainer.py::finetune).
    learn_pallas_grads: bool = False
    mesh_dp: int = 1                               # data-parallel axis (incidents)
    mesh_graph: int = 1                            # graph-parallel axis (node shards)
    # graft-tide stretched the topology ladders to 500k-pod configs: the
    # 262144/524288 node rungs and the 1M/4M edge rungs are DMA-tier
    # territory (the resident fused tick refuses them — see
    # ops.pallas_segment.fused_tick_vmem_bytes). Existing rungs are
    # untouched so every previously-chosen static shape stays identical.
    # (rungs declared in analysis/ladders.py — graft-lattice — where the
    # ladder-gap check pins 500k-pod coverage and the DMA block alignment)
    node_bucket_sizes: tuple = _ladders.NODE_BUCKET_SIZES
    edge_bucket_sizes: tuple = _ladders.EDGE_BUCKET_SIZES
    incident_bucket_sizes: tuple = _ladders.INCIDENT_BUCKET_SIZES
    # NOTE: there is deliberately no pallas flag — the fused rules kernel
    # measured at parity with the XLA path at config 3 (both ~0.2 ms/pass
    # on v5e-1) and lives in experiments/pallas_rules.py until it wins

    @property
    def environment(self) -> str:
        """Normalized short environment name (dev|staging|uat|prod)."""
        e = self.app_env.lower()
        return {"development": "dev", "production": "prod"}.get(e, e)


_ENV_PREFIX = "KAEG_"


def load_settings(**overrides: Any) -> Settings:
    """Build Settings from KAEG_* env vars, then apply explicit overrides."""
    kwargs: dict[str, Any] = {}
    for f in fields(Settings):
        env_name = _ENV_PREFIX + f.name.upper()
        if env_name not in os.environ:
            continue
        raw = os.environ[env_name]
        if isinstance(f.default, bool):
            kwargs[f.name] = raw.strip().lower() in ("1", "true", "yes", "on")
        elif isinstance(f.default, int):
            kwargs[f.name] = int(raw)
        elif isinstance(f.default, float):
            kwargs[f.name] = float(raw)
        elif isinstance(f.default, tuple):
            kwargs[f.name] = _parse_buckets(raw)
        else:
            kwargs[f.name] = raw
    kwargs.update(overrides)
    return Settings(**kwargs)


@lru_cache(maxsize=1)
def get_settings() -> Settings:
    """Process-wide lazy singleton (reference settings.py:146-153)."""
    return load_settings()
