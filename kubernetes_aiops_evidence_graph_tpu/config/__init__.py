from .settings import Settings, get_settings, load_settings

__all__ = ["Settings", "get_settings", "load_settings"]
