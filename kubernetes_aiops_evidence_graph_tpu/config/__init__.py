from .settings import Settings, get_settings, load_settings, settings

__all__ = ["Settings", "get_settings", "load_settings", "settings"]
