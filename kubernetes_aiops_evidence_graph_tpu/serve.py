"""`python -m kubernetes_aiops_evidence_graph_tpu.serve` — run the platform."""
from .app import main

if __name__ == "__main__":
    main()
