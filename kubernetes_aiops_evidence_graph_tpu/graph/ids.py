"""Canonical graph-entity id scheme.

Matches the reference's "{type}:{namespace}:{name}" convention
(e.g. "pod:default:api-server-7d4f5b6c8-xyz", evidence.py:122) so store keys
and subgraph payloads are interchangeable.
"""
from __future__ import annotations


def incident_id(uid: str) -> str:
    return f"incident:{uid}"


def pod_id(namespace: str, name: str) -> str:
    return f"pod:{namespace}:{name}"


def deployment_id(namespace: str, name: str) -> str:
    return f"deployment:{namespace}:{name}"


def replicaset_id(namespace: str, name: str) -> str:
    return f"replicaset:{namespace}:{name}"


def node_id(name: str) -> str:
    return f"node:{name}"


def service_id(namespace: str, name: str) -> str:
    return f"service:{namespace}:{name}"


def hpa_id(namespace: str, name: str) -> str:
    return f"hpa:{namespace}:{name}"


def configmap_id(namespace: str, name: str) -> str:
    return f"configmap:{namespace}:{name}"


def change_id(namespace: str, name: str, revision: int | str) -> str:
    return f"change:{namespace}:{name}:{revision}"


def namespace_id(name: str) -> str:
    return f"namespace:{name}"
