"""Evidence → graph assembly.

Replaces the reference's ``build_evidence_graph`` activity
(activities.py:96-123): collector results (entities + relations) merge into
the in-memory store, and evidence payloads are folded onto the graph nodes
they describe so the tensorizer (snapshot.py) sees every signal the CPU
rules engine would see in the raw evidence list — the invariant the
CPU-vs-TPU parity tests enforce.
"""
from __future__ import annotations

from typing import Iterable

from ..models import CollectorResult, Evidence, EvidenceType, GraphEntity, GraphRelation, Incident
from . import ids
from .store import EvidenceGraphStore

# evidence.data keys that become node properties the feature extractor reads
_MERGE_KEYS = (
    "waiting_reason", "terminated_reason", "restart_count", "ready",
    "not_ready_seconds", "readiness_probe_failing", "phase",
    "error_count", "patterns_found", "network_error_count",
    "is_recent_change", "image_changed", "config_changed", "changed_at",
    "memory_usage_high", "cpu_throttling", "hpa_at_max", "at_max",
    "latency_high", "conditions", "unavailable_replicas",
)

_TYPE_PREFIX = {
    EvidenceType.KUBERNETES_POD: "pod",
    EvidenceType.KUBERNETES_DEPLOYMENT: "deployment",
    EvidenceType.KUBERNETES_REPLICASET: "replicaset",
    EvidenceType.KUBERNETES_NODE: "node",
    EvidenceType.KUBERNETES_SERVICE: "service",
    EvidenceType.KUBERNETES_CONFIGMAP: "configmap",
    EvidenceType.KUBERNETES_HPA: "hpa",
    EvidenceType.LOG_SIGNAL: "service",
    EvidenceType.METRIC_SIGNAL: "service",
    EvidenceType.DEPLOY_CHANGE: "deployment",
    EvidenceType.IMAGE_CHANGE: "deployment",
    EvidenceType.CONFIG_CHANGE: "configmap",
}


_PREFIX_LABEL = {
    "pod": "Pod", "deployment": "Deployment", "replicaset": "ReplicaSet",
    "node": "Node", "service": "Service", "configmap": "ConfigMap", "hpa": "HPA",
}


def _metric_flags(data: dict) -> dict:
    """Translate a metric evidence payload into the node-property flags the
    feature extractor reads — the same thresholds the CPU signal fold applies
    (rules_engine.py:337-350), so both backends see identical booleans.
    Thresholds read the series eval value via metric_eval (the family's
    windowed statistic), exactly like rca/signals._fold_metric."""
    from ..utils.metricseries import metric_eval
    flags: dict = {}
    query_name = data.get("query_name", "") or ""
    value = metric_eval(data)
    if "memory" in query_name and data.get("is_anomalous") and value > 90:
        flags["memory_usage_high"] = True
    if "hpa" in query_name and "max" in query_name and value >= 1:
        flags["hpa_at_max"] = True
    if "latency" in query_name and value > 1:
        flags["latency_high"] = True
    if "throttl" in query_name and value > 0.5:
        flags["cpu_throttling"] = True
    return flags


class GraphBuilder:
    """Folds incidents + collector output into an EvidenceGraphStore."""

    def __init__(self, store: EvidenceGraphStore | None = None) -> None:
        self.store = store or EvidenceGraphStore()

    def add_incident(self, incident: Incident) -> str:
        """Create the incident node (reference kubernetes_collector.py:90-102
        creates it inside the collector; here it is the builder's job)."""
        nid = ids.incident_id(str(incident.id))
        self.store.upsert_entity(GraphEntity(
            id=nid,
            type="Incident",
            properties={
                "title": incident.title,
                "severity": incident.severity.value,
                "status": incident.status.value,
                "namespace": incident.namespace,
                "service": incident.service or "",
                "fingerprint": incident.fingerprint,
                "started_at": incident.started_at.isoformat(),
            },
        ))
        return nid

    def ingest(self, incident: Incident, results: Iterable[CollectorResult]) -> dict:
        """Merge one incident's collector results into the graph."""
        inc_node = self.add_incident(incident)
        n_entities = n_relations = n_evidence = 0
        for result in results:
            if result.entities:
                n_entities += self.store.upsert_entities(result.entities)
            if result.relations:
                n_relations += self.store.upsert_relations(result.relations)
            for ev in result.evidence:
                self._apply_evidence(inc_node, ev)
                n_evidence += 1
        return {
            "incident_node": inc_node,
            "entities": n_entities,
            "relations": n_relations,
            "evidence": n_evidence,
        }

    def _apply_evidence(self, incident_node: str, ev: Evidence) -> None:
        """Attach an evidence payload to the node it describes, creating the
        node and an Incident-AFFECTS edge if the collector didn't emit one."""
        prefix = _TYPE_PREFIX.get(ev.evidence_type)
        if prefix is None:
            return  # events etc. carry no node-level features
        node_id = (
            f"{prefix}:{ev.entity_name}" if prefix == "node"
            else f"{prefix}:{ev.entity_namespace}:{ev.entity_name}"
        )
        props = {k: ev.data[k] for k in _MERGE_KEYS if k in ev.data}
        if ev.evidence_type == EvidenceType.METRIC_SIGNAL:
            props.update(_metric_flags(ev.data))
        props["signal_strength"] = max(
            float(ev.signal_strength),
            float((self.store.get_node(node_id) or {}).get("properties", {}).get("signal_strength", 0.0)),
        )
        if ev.is_anomaly:
            props["is_anomaly"] = True
        label = _PREFIX_LABEL[prefix]
        self.store.upsert_entities([GraphEntity(id=node_id, type=label, properties=props)])
        self.store.upsert_relations([GraphRelation(
            source_id=incident_node, target_id=node_id, relation_type="AFFECTS",
        )])
