"""GraphSnapshot — the tensorized, XLA-ready view of the evidence graph.

This is the data structure the whole TPU path consumes: dense node-feature
matrix + COO edge lists, padded to bucket ladders (utils/padding.py) so jit
caches stay warm under pod churn. It replaces the reference's per-incident
Cypher traversals (neo4j.py:169-201) with one whole-graph array view that
scores *all* incidents in a single batched pass (BASELINE.json north star).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import numpy as np

from ..config import Settings, get_settings
from ..utils.padding import bucket_for, pad_to
from ..utils.timeutils import parse_iso, to_epoch_s, utcnow
from .schema import (
    DIM,
    EntityKind,
    F,
    LOG_PATTERN_FEATURES,
    NODE_CONDITION_FEATURES,
    RelationKind,
    TERMINATED_REASON_FEATURES,
    WAITING_REASON_FEATURES,
)
from .store import EvidenceGraphStore, _Node

# Per-relation edge-slice capacity ladder (relation-bucketed layout): each
# RelationKind's contiguous slice is padded to a ladder value so the static
# offset tuple — a jit cache key for the bucketed GNN kernel — is drawn
# from a small discrete set instead of minting a recompile per edge-count
# drift. Powers of two up to 8192, then multiples of 8192: the bucketed
# kernel's device time scales with PADDED edge rows (gather + scatter both
# walk them), so big slices cap the inflation at ~6% instead of the ~2x a
# pure power-of-two ladder costs (measured 459520 padded for 273238 live
# at the 50k-node bench config; the stepped ladder lands at 287488).
# 8192-multiples keep slice bases tile-aligned. Shared by build_snapshot,
# parallel/partition.py and the streaming edge mirror
# (rca/gnn_streaming.py).
# graft-tide stretched the ladder with named 8192-multiple rungs
# (16384/24576/32768) for 500k-pod edge profiles. The rungs are exactly
# the capacities the old beyond-top rule produced, and the step stays
# anchored at _REL_SLICE_STEP above the ladder, so EVERY count rounds to
# the same capacity as before the stretch — no static offset tuple, jit
# cache key or cost baseline shifts.
# graft-lattice: the rungs and step live in the declared ladder
# registry (analysis/ladders.py); these aliases keep the historical
# import surface (build_snapshot, parallel/partition.py, the streaming
# edge mirror) pointing at the one source of truth
from ..analysis.ladders import (REL_SLICE_BUCKETS,
                                REL_SLICE_STEP as _REL_SLICE_STEP)


def rel_slice_offsets(counts, slack: float = 0.0,
                      min_cap: int = 0,
                      buckets: tuple[int, ...] = REL_SLICE_BUCKETS,
                      ) -> tuple[int, ...]:
    """[R+1] static offsets for a relation-bucketed edge layout: slice r
    spans ``[off[r], off[r+1])`` with capacity ``count_r`` rounded up the
    ladder — power-of-two below its top rung, next multiple of
    ``_REL_SLICE_STEP`` above — plus ``slack`` growth headroom. A
    relation with no edges gets a zero-width slice unless ``min_cap``
    reserves room (the streaming mirror does, so first-edge churn of a
    new relation doesn't force an immediate re-mirror)."""
    offs = [0]
    # anchored at _REL_SLICE_STEP (NOT buckets[-1]): the graft-tide rung
    # stretch must not coarsen beyond-ladder rounding
    step = max(int(buckets[-1]), _REL_SLICE_STEP) \
        if buckets is not REL_SLICE_BUCKETS else _REL_SLICE_STEP
    for c in counts:
        need = max(int(np.ceil(int(c) * (1.0 + slack))), min_cap)
        if need <= 0:
            cap = 0
        elif need <= buckets[-1]:
            cap = bucket_for(need, buckets)
        else:
            cap = -(-need // step) * step
        offs.append(offs[-1] + cap)
    return tuple(offs)


def extract_node_features(node: _Node, now_s: float | None = None) -> np.ndarray:
    """Fold a node's property bag into the fixed feature vector.

    Tensor analog of the reference's per-evidence signal fold
    (rules_engine.py:292-357): the same keys are read, but from graph-node
    properties (set by collectors/builder) instead of evidence dicts.
    """
    f = np.zeros(DIM, dtype=np.float32)
    p = node.properties

    wr = p.get("waiting_reason")
    if wr in WAITING_REASON_FEATURES:
        f[WAITING_REASON_FEATURES[wr]] = 1.0
    tr = p.get("terminated_reason")
    if tr in TERMINATED_REASON_FEATURES:
        f[TERMINATED_REASON_FEATURES[tr]] = 1.0

    f[F.RESTART_COUNT] = float(p.get("restart_count", 0) or 0)
    if p.get("ready") is False:
        not_ready_s = float(p.get("not_ready_seconds", 0) or 0)
        if not_ready_s >= 300:  # rule readiness_probe_failing duration_seconds: 300
            f[F.POD_NOT_READY] = 1.0
    if p.get("readiness_probe_failing"):
        f[F.READINESS_PROBE_FAILING] = 1.0

    f[F.ERROR_COUNT] = float(p.get("error_count", 0) or 0)
    for pat in p.get("patterns_found", ()) or ():
        idx = LOG_PATTERN_FEATURES.get(pat)
        if idx is not None:
            f[idx] = 1.0

    if p.get("is_recent_change"):
        f[F.HAS_RECENT_DEPLOY] = 1.0
    if p.get("image_changed"):
        f[F.HAS_IMAGE_CHANGE] = 1.0
    if p.get("config_changed"):
        f[F.HAS_CONFIG_CHANGE] = 1.0
    ts = p.get("changed_at")
    if ts is not None:
        when = parse_iso(ts) if isinstance(ts, str) else ts
        age_min = max(0.0, ((now_s or to_epoch_s(utcnow())) - to_epoch_s(when)) / 60.0)
        f[F.CHANGE_RECENCY] = max(0.0, 1.0 - age_min / 30.0)  # 30min window, deploy_diff_collector.py:93-215

    if p.get("memory_usage_high"):
        f[F.MEMORY_USAGE_HIGH] = 1.0
    if p.get("cpu_throttling"):
        f[F.CPU_THROTTLING] = 1.0
    if p.get("hpa_at_max") or p.get("at_max"):
        f[F.HPA_AT_MAX] = 1.0
    if p.get("latency_high"):
        f[F.LATENCY_HIGH] = 1.0

    conds = p.get("conditions") or {}
    if node.kind == EntityKind.NODE:
        ready = conds.get("Ready")
        status = ready.get("status") if isinstance(ready, dict) else ready
        if status is not None and status != "True":
            f[F.NODE_NOT_READY] = 1.0
        for cname, idx in NODE_CONDITION_FEATURES.items():
            if cname == "NotReady":
                continue
            c = conds.get(cname)
            cstatus = c.get("status") if isinstance(c, dict) else c
            if cstatus == "True":
                f[idx] = 1.0

    if node.kind == EntityKind.POD and (
        p.get("waiting_reason")
        or p.get("terminated_reason")
        or float(p.get("restart_count", 0) or 0) > 3  # PROBLEM_POD_RESTARTS
        or p.get("ready") is False
    ):
        f[F.POD_PROBLEM] = 1.0

    f[F.NETWORK_ERROR_COUNT] = float(p.get("network_error_count", 0) or 0)
    f[F.SIGNAL_STRENGTH] = float(p.get("signal_strength", 0.0) or 0.0)
    if p.get("is_anomaly"):
        f[F.IS_ANOMALY] = 1.0
    if float(p.get("unavailable_replicas", 0) or 0) > 0:
        f[F.DEPLOY_UNAVAILABLE] = 1.0

    return f


@dataclass(frozen=True)
class GraphSnapshot:
    """Immutable padded tensor view of the evidence graph.

    Shapes (P* = padded to bucket):
      node_kind  int32  [Pn]      features  float32 [Pn, DIM]
      node_mask  f32    [Pn]      (1.0 real / 0.0 pad)
      edge_src   int32  [Pe]      edge_dst  int32 [Pe]   edge_rel int32 [Pe]
      edge_mask  f32    [Pe]      (padded edges self-loop on pad node 0 weight)
      incident_nodes int32 [Pi]   incident_mask f32 [Pi]

    Edge layout contract (relation-bucketed): edges are sorted by
    ``(rel, dst)`` and grouped into per-relation contiguous slices —
    relation r owns ``[rel_offsets[r], rel_offsets[r+1])``, live prefix
    dst-sorted, slice tail padded (mask 0, rel -1, dst pinned to the last
    node row so each slice stays non-decreasing in dst). The static
    ``rel_offsets`` tuple is what lets the GNN's bucketed kernel slice per
    relation with one [H, H] matmul each (rca/gnn.py); COO consumers that
    filter by mask/rel stay order-insensitive.
    """
    node_ids: tuple[str, ...]
    incident_ids: tuple[str, ...]
    num_nodes: int
    num_edges: int
    num_incidents: int
    node_kind: np.ndarray
    features: np.ndarray
    node_mask: np.ndarray
    edge_src: np.ndarray
    edge_dst: np.ndarray
    edge_rel: np.ndarray
    edge_mask: np.ndarray
    incident_nodes: np.ndarray
    incident_mask: np.ndarray
    version: int = 0
    rel_offsets: tuple[int, ...] = ()   # [R+1] per-relation edge slices

    @property
    def padded_nodes(self) -> int:
        return int(self.node_kind.shape[0])

    @property
    def padded_edges(self) -> int:
        return int(self.edge_src.shape[0])

    @property
    def padded_incidents(self) -> int:
        return int(self.incident_nodes.shape[0])

    def typed_edges(self, kind: RelationKind) -> tuple[np.ndarray, np.ndarray]:
        """(src, dst) for one relation kind, unpadded."""
        sel = (self.edge_rel == int(kind)) & (self.edge_mask > 0)
        return self.edge_src[sel], self.edge_dst[sel]

    def index_of(self, node_id: str) -> int:
        return self.node_ids.index(node_id)


def build_snapshot(
    store: EvidenceGraphStore,
    settings: Settings | None = None,
    now_s: float | None = None,
    undirected: bool = True,
    slack: float = 0.0,
) -> GraphSnapshot:
    """Tensorize the store. With ``undirected=True`` every edge is emitted in
    both directions — matching apoc.path.subgraphAll's undirected expansion
    (neo4j.py:174) so propagation reaches owners and dependents alike.

    ``slack`` reserves growth headroom when picking buckets (the streaming
    scorer passes 1/3 so node creations and incident arrivals land in free
    padded rows instead of forcing a rebuild — and a rebuild's recompile
    storm — mid-stream)."""
    cfg = settings or get_settings()
    nodes, edges = store._raw()

    def _pad(k: int) -> int:
        return max(int(np.ceil(k * (1.0 + slack))), 1)

    n = len(nodes)
    pn = bucket_for(_pad(max(n, 1)), cfg.node_bucket_sizes)

    node_kind = np.zeros(pn, dtype=np.int32)
    features = np.zeros((pn, DIM), dtype=np.float32)
    node_mask = np.zeros(pn, dtype=np.float32)
    incident_rows: list[int] = []
    incident_ids: list[str] = []

    for i, node in enumerate(nodes):
        node_kind[i] = int(node.kind)
        features[i] = extract_node_features(node, now_s=now_s)
        node_mask[i] = 1.0
        if node.kind == EntityKind.INCIDENT:
            incident_rows.append(i)
            incident_ids.append(node.id)

    raw_edges: list[tuple[int, int, int]] = []
    id_to_idx = {node.id: i for i, node in enumerate(nodes)}
    for e in edges:
        s, d = id_to_idx[e.src], id_to_idx[e.dst]
        raw_edges.append((s, d, int(e.kind)))
        if undirected:
            raw_edges.append((d, s, int(e.kind)))

    m = len(raw_edges)
    num_rels = len(RelationKind)
    counts = np.zeros(num_rels, dtype=np.int64)
    arr = np.asarray(raw_edges, dtype=np.int32) if m else None
    if m:
        counts = np.bincount(arr[:, 2], minlength=num_rels)
    # relation-bucketed layout: live edges sorted by (rel, dst) into one
    # contiguous padded slice per relation (static offsets). Each slice's
    # live prefix is dst-sorted, so the GNN's per-slice segment-sums keep
    # the indices_are_sorted fast path (measured 1.9x on the v5e scatter);
    # COO consumers filter by mask/rel and stay order-insensitive.
    rel_offsets = rel_slice_offsets(counts)
    pe = max(int(rel_offsets[-1]), 1)
    edge_src = np.zeros(pe, dtype=np.int32)
    # padding dst = LAST node row, not 0: keeps every slice monotone in
    # dst through its padded tail (the mask-zeroed messages add 0.0 to
    # that row either way)
    edge_dst = np.full(pe, pn - 1, dtype=np.int32)
    edge_rel = np.full(pe, -1, dtype=np.int32)
    edge_mask = np.zeros(pe, dtype=np.float32)
    if m:
        order = np.lexsort((arr[:, 1], arr[:, 2]))   # rel major, dst minor
        arr = arr[order]
        pos = 0
        for r in range(num_rels):
            c = int(counts[r])
            lo = rel_offsets[r]
            edge_src[lo:lo + c] = arr[pos:pos + c, 0]
            edge_dst[lo:lo + c] = arr[pos:pos + c, 1]
            edge_rel[lo:lo + c] = arr[pos:pos + c, 2]
            edge_mask[lo:lo + c] = 1.0
            pos += c

    ni = len(incident_rows)
    pi = bucket_for(_pad(max(ni, 1)), cfg.incident_bucket_sizes)
    incident_nodes = np.zeros(pi, dtype=np.int32)
    incident_mask = np.zeros(pi, dtype=np.float32)
    if ni:
        incident_nodes[:ni] = np.asarray(incident_rows, dtype=np.int32)
        incident_mask[:ni] = 1.0

    return GraphSnapshot(
        node_ids=tuple(node.id for node in nodes),
        incident_ids=tuple(incident_ids),
        num_nodes=n,
        num_edges=m,
        num_incidents=ni,
        node_kind=node_kind,
        features=features,
        node_mask=node_mask,
        edge_src=edge_src,
        edge_dst=edge_dst,
        edge_rel=edge_rel,
        edge_mask=edge_mask,
        incident_nodes=incident_nodes,
        incident_mask=incident_mask,
        version=store.version,
        rel_offsets=rel_offsets,
    )
