from .builder import GraphBuilder
from .schema import DIM, EntityKind, F, RelationKind
from .snapshot import GraphSnapshot, build_snapshot, extract_node_features
from .store import EvidenceGraphStore

__all__ = [
    "DIM", "EntityKind", "F", "RelationKind",
    "EvidenceGraphStore", "GraphBuilder",
    "GraphSnapshot", "build_snapshot", "extract_node_features",
]
