"""Evidence-graph schema: entity kinds, relation kinds, node-feature layout.

Entity labels and relation types mirror the reference's Neo4j schema
(neo4j.py:299-320 uniqueness constraints; kubernetes_collector.py:296-313 and
neo4j.py:204-265 relation usage). The node-feature layout is new: it is the
tensorized form of the reference rules engine's signal dict
(rules_engine.py:274-290) so that per-incident signals can be computed on
TPU as one batched reduction over the graph instead of a Python fold over
evidence dicts.

Every feature has a reduction mode describing how per-node values fold into
a per-incident signal across the K-hop neighborhood:

* ``or``  — flag; incident signal = any reachable node has it (count > 0)
* ``sum`` — additive count (e.g. error_count, rules_engine.py:335)
* ``max`` — maximum over reachable nodes (e.g. restart_count,
  rules_engine.py:319)

``or`` and ``sum`` are both computed by one reach@features matmul on the
MXU; ``max`` features get a segment-max pass.
"""
from __future__ import annotations

from enum import IntEnum


class EntityKind(IntEnum):
    INCIDENT = 0
    POD = 1
    DEPLOYMENT = 2
    REPLICASET = 3
    NODE = 4
    SERVICE = 5
    HPA = 6
    CONFIGMAP = 7
    CHANGE_EVENT = 8
    NAMESPACE = 9
    CONTAINER = 10

    @classmethod
    def from_label(cls, label: str) -> "EntityKind":
        return _LABEL_TO_KIND.get(label, cls.CONTAINER)


_LABEL_TO_KIND = {
    "Incident": EntityKind.INCIDENT,
    "Pod": EntityKind.POD,
    "Deployment": EntityKind.DEPLOYMENT,
    "ReplicaSet": EntityKind.REPLICASET,
    "Node": EntityKind.NODE,
    "Service": EntityKind.SERVICE,
    "HPA": EntityKind.HPA,
    "ConfigMap": EntityKind.CONFIGMAP,
    "ChangeEvent": EntityKind.CHANGE_EVENT,
    "Namespace": EntityKind.NAMESPACE,
    "Container": EntityKind.CONTAINER,
}

KIND_TO_LABEL = {v: k for k, v in _LABEL_TO_KIND.items()}


class RelationKind(IntEnum):
    AFFECTS = 0            # Incident -> Pod/Deployment/... (kubernetes_collector.py:306)
    SCHEDULED_ON = 1       # Pod -> Node (kubernetes_collector.py:300)
    OWNS = 2               # Deployment -> ReplicaSet -> Pod (neo4j.py:237)
    SELECTS = 3            # Service -> Pod
    CALLS = 4              # Service -> Service (neo4j.py:254-278)
    HAS_RECENT_CHANGE = 5  # Deployment -> ChangeEvent (deploy_diff_collector.py:233-268)
    CORRELATES_WITH = 6    # Incident -> ChangeEvent
    IN_NAMESPACE = 7       # any -> Namespace (new)
    MOUNTS = 8             # Pod -> ConfigMap (new)

    @classmethod
    def from_label(cls, label: str) -> "RelationKind":
        return _REL_TO_KIND[label]


_REL_TO_KIND = {
    "AFFECTS": RelationKind.AFFECTS,
    "SCHEDULED_ON": RelationKind.SCHEDULED_ON,
    "OWNS": RelationKind.OWNS,
    "SELECTS": RelationKind.SELECTS,
    "CALLS": RelationKind.CALLS,
    "HAS_RECENT_CHANGE": RelationKind.HAS_RECENT_CHANGE,
    "CORRELATES_WITH": RelationKind.CORRELATES_WITH,
    "IN_NAMESPACE": RelationKind.IN_NAMESPACE,
    "MOUNTS": RelationKind.MOUNTS,
}

REL_TO_LABEL = {v: k for k, v in _REL_TO_KIND.items()}


# ---------------------------------------------------------------------------
# Node feature layout
# ---------------------------------------------------------------------------

class F(IntEnum):
    """Feature indices into the dense node-feature matrix [N, DIM].

    Groups mirror the reference signal dict keys (rules_engine.py:274-290):
    waiting_reasons / terminated_reasons sets become one-hot flags; the
    booleans become flags; counters keep their reference reduction.
    """
    # container waiting reasons (kubernetes_collector.py:269-285)
    W_CRASHLOOPBACKOFF = 0
    W_IMAGEPULLBACKOFF = 1
    W_ERRIMAGEPULL = 2
    W_IMAGEINSPECTERROR = 3
    # container terminated reasons
    T_OOMKILLED = 4
    T_CONTAINERCANNOTRUN = 5
    T_CREATECONTAINERCONFIGERROR = 6
    T_ERROR = 7
    # pod state
    RESTART_COUNT = 8          # reduce: max (rules_engine.py:319)
    POD_NOT_READY = 9          # not-ready >= 300s (rule readiness_probe_failing)
    READINESS_PROBE_FAILING = 10
    # logs (logs_collector.py:20-31 pattern categories + rule vocab)
    ERROR_COUNT = 11           # reduce: sum (rules_engine.py:335)
    LOG_ERROR = 12
    LOG_CRITICAL = 13
    LOG_OOM = 14
    LOG_NETWORK = 15
    LOG_AUTH = 16
    LOG_MISSING = 17
    LOG_NULL_POINTER = 18
    LOG_CONNECTION = 19
    LOG_DISK = 20
    LOG_TLS = 21
    LOG_TIMEOUT = 22
    # changes (deploy_diff_collector.py)
    HAS_RECENT_DEPLOY = 23
    HAS_IMAGE_CHANGE = 24
    HAS_CONFIG_CHANGE = 25
    CHANGE_RECENCY = 26        # reduce: max; 1 - age/30min clamped to [0,1]
    # metrics (metrics_collector.py:247-329 thresholds)
    MEMORY_USAGE_HIGH = 27
    CPU_THROTTLING = 28
    HPA_AT_MAX = 29
    LATENCY_HIGH = 30
    # node conditions (kubernetes_collector.py:504-557)
    NODE_NOT_READY = 31
    NODE_DISK_PRESSURE = 32
    NODE_MEMORY_PRESSURE = 33
    NODE_PID_PRESSURE = 34
    NODE_NETWORK_UNAVAILABLE = 35
    # misc
    NETWORK_ERROR_COUNT = 36   # reduce: sum
    SIGNAL_STRENGTH = 37       # reduce: max
    IS_ANOMALY = 38
    DEPLOY_UNAVAILABLE = 39
    POD_PROBLEM = 40           # derived: any waiting/terminated reason,
                               # restarts > PROBLEM_POD_RESTARTS, or not ready


DIM = 48  # padded past max(F)+1 so new features don't change compiled shapes

# Reduction masks (index lists) — everything not listed is "or"/"sum"-safe
# through the matmul; MAX_FEATURES additionally get a segment-max pass.
MAX_FEATURES = (int(F.RESTART_COUNT), int(F.CHANGE_RECENCY), int(F.SIGNAL_STRENGTH))
SUM_FEATURES = (int(F.ERROR_COUNT), int(F.NETWORK_ERROR_COUNT))

WAITING_REASON_FEATURES = {
    "CrashLoopBackOff": F.W_CRASHLOOPBACKOFF,
    "ImagePullBackOff": F.W_IMAGEPULLBACKOFF,
    "ErrImagePull": F.W_ERRIMAGEPULL,
    "ImageInspectError": F.W_IMAGEINSPECTERROR,
}

TERMINATED_REASON_FEATURES = {
    "OOMKilled": F.T_OOMKILLED,
    "ContainerCannotRun": F.T_CONTAINERCANNOTRUN,
    "CreateContainerConfigError": F.T_CREATECONTAINERCONFIGERROR,
    "Error": F.T_ERROR,
}

LOG_PATTERN_FEATURES = {
    "error": F.LOG_ERROR,
    "critical": F.LOG_CRITICAL,
    "oom": F.LOG_OOM,
    "network": F.LOG_NETWORK,
    "auth": F.LOG_AUTH,
    "missing": F.LOG_MISSING,
    "null_pointer": F.LOG_NULL_POINTER,
    "connection": F.LOG_CONNECTION,
    "disk": F.LOG_DISK,
    "tls": F.LOG_TLS,
    "timeout": F.LOG_TIMEOUT,
}

NODE_CONDITION_FEATURES = {
    "NotReady": F.NODE_NOT_READY,
    "DiskPressure": F.NODE_DISK_PRESSURE,
    "MemoryPressure": F.NODE_MEMORY_PRESSURE,
    "PIDPressure": F.NODE_PID_PRESSURE,
    "NetworkUnavailable": F.NODE_NETWORK_UNAVAILABLE,
}
