"""Cluster-topology sync: load the full cluster into the evidence graph.

The reference's Neo4j graph only ever contains entities touched by incident
evidence; BASELINE.json's large configs ("50k-node multi-namespace mesh
topology") presuppose a continuously-synced topology layer — the kube-state
analog. This module bulk-loads every pod/node/deployment/service/HPA plus
OWNS / SCHEDULED_ON / SELECTS / CALLS edges from a cluster backend into the
store, so incident scoring and 3-hop propagation run against the real mesh.
"""
from __future__ import annotations

from ..models import GraphEntity, GraphRelation
from . import ids
from .store import EvidenceGraphStore


def sync_topology(cluster, store: EvidenceGraphStore) -> dict:
    """Bulk-load FakeCluster/real-backend state into the graph store."""
    entities: list[GraphEntity] = []
    relations: list[GraphRelation] = []

    for n in cluster.nodes.values():
        entities.append(GraphEntity(
            id=ids.node_id(n.name), type="Node",
            properties={"name": n.name,
                        "conditions": {k: {"status": v} for k, v in n.conditions.items()}},
        ))

    for d in cluster.deployments.values():
        dep = ids.deployment_id(d.namespace, d.name)
        entities.append(GraphEntity(
            id=dep, type="Deployment",
            properties={"replicas": d.replicas, "ready_replicas": d.ready_replicas,
                        "unavailable_replicas": max(0, d.replicas - d.ready_replicas),
                        "revision": d.revision},
        ))

    for s in cluster.services.values():
        svc = ids.service_id(s.namespace, s.name)
        entities.append(GraphEntity(id=svc, type="Service",
                                    properties={"name": s.name, "namespace": s.namespace}))
        for callee in s.calls:
            relations.append(GraphRelation(
                source_id=svc, target_id=ids.service_id(s.namespace, callee),
                relation_type="CALLS"))

    for p in cluster.pods.values():
        pod = ids.pod_id(p.namespace, p.name)
        entities.append(GraphEntity(
            id=pod, type="Pod",
            properties={"waiting_reason": p.waiting_reason,
                        "terminated_reason": p.terminated_reason,
                        "restart_count": p.restart_count, "ready": p.ready,
                        "phase": p.phase},
        ))
        relations.append(GraphRelation(
            source_id=pod, target_id=ids.node_id(p.node), relation_type="SCHEDULED_ON"))
        relations.append(GraphRelation(
            source_id=ids.deployment_id(p.namespace, p.deployment), target_id=pod,
            relation_type="OWNS"))
        relations.append(GraphRelation(
            source_id=ids.service_id(p.namespace, p.service), target_id=pod,
            relation_type="SELECTS"))

    for h in cluster.hpas.values():
        hpa = ids.hpa_id(h.namespace, h.name)
        entities.append(GraphEntity(
            id=hpa, type="HPA",
            properties={"at_max": h.at_max or h.current_replicas >= h.max_replicas,
                        "current_replicas": h.current_replicas,
                        "max_replicas": h.max_replicas},
        ))
        relations.append(GraphRelation(
            source_id=hpa, target_id=ids.deployment_id(h.namespace, h.deployment),
            relation_type="OWNS"))

    ne = store.upsert_entities(entities)
    nr = store.upsert_relations(relations)
    return {"entities": ne, "relations": nr}
