"""In-memory evidence-graph store — the Neo4j/GraphService replacement.

Capability parity with the reference GraphService (src/database/neo4j.py:67-320):
MERGE-semantics upserts, depth-limited incident subgraphs
(apoc.path.subgraphAll, neo4j.py:169-201), time-windowed related changes
(:204-228), affected-by-node traversal (:231-251), service dependency
up/downstream (:254-278), and per-incident cleanup (:281-296).

Unlike the reference — which issues one Bolt round-trip per node/edge
(neo4j.py:95-166) — upserts here are O(1) dict operations and batch calls
are true batches, and the whole graph tensorizes into a
:class:`~.snapshot.GraphSnapshot` for TPU scoring.
"""
from __future__ import annotations

import itertools
import threading
from collections import deque
from dataclasses import dataclass, field
from datetime import datetime
from typing import Any, Iterable, Optional

from ..models import GraphEntity, GraphRelation
from ..utils.timeutils import parse_iso
from .schema import EntityKind, RelationKind


@dataclass
class _Node:
    id: str
    kind: EntityKind
    label: str
    index: int                      # monotone insertion order (sort key ONLY —
    #   removals leave holes; dense row numbers are assigned at COO build)
    properties: dict[str, Any] = field(default_factory=dict)


@dataclass
class _Edge:
    src: str
    dst: str
    kind: RelationKind
    properties: dict[str, Any] = field(default_factory=dict)


class EvidenceGraphStore:
    """Mutable, thread-safe, in-memory property graph."""

    # below this many nodes the Python BFS beats the cost of materializing
    # the COO index for the native kernel
    _NATIVE_BFS_MIN_NODES = 2048

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._nodes: dict[str, _Node] = {}
        self._edges: dict[tuple[str, str, RelationKind], _Edge] = {}
        self._out: dict[str, set[tuple[str, RelationKind]]] = {}
        self._in: dict[str, set[tuple[str, RelationKind]]] = {}
        self._version = 0  # bumps on every mutation; snapshot cache key
        self._next_index = 0  # monotone: removal never reassigns indices
        self._coo_cache: tuple[int, list[str], dict[str, int], Any, Any] | None = None
        # change journal: every structural mutation appends one record so a
        # resident StreamingScorer can mirror the graph without rebuilding
        # (the serving-path seam; see rca/streaming.py sync()). Bounded: a
        # consumer that falls further behind than the buffer must rebuild.
        self._journal: deque[tuple] = deque(maxlen=200_000)
        self._seq = 0

    def _jrec(self, *rec: Any) -> None:
        """Append one journal record. Caller must hold the lock."""
        self._seq += 1
        self._journal.append((self._seq, *rec))

    @property
    def journal_seq(self) -> int:
        with self._lock:
            return self._seq

    def journal_since(self, seq: int) -> tuple[list[tuple], int, bool]:
        """(records with seq > `seq`, current seq, truncated). `truncated`
        means records after `seq` were evicted from the bounded buffer —
        the consumer must fall back to a full rebuild."""
        with self._lock:
            if not self._journal:
                return [], self._seq, seq < self._seq
            oldest = self._journal[0][0]
            if seq + 1 < oldest:
                return [], self._seq, True
            # seqs are contiguous: slice by offset instead of scanning
            start = seq + 1 - oldest
            return list(itertools.islice(self._journal, start, None)), \
                self._seq, False

    # -- mutation ---------------------------------------------------------

    def upsert_entity(self, entity: GraphEntity) -> None:
        self.upsert_entities([entity])

    def upsert_entities(self, entities: Iterable[GraphEntity]) -> int:
        """Batch MERGE of nodes (reference neo4j.py:95-112, but one lock +
        dict ops instead of one session.run per entity)."""
        n = 0
        with self._lock:
            for e in entities:
                node = self._nodes.get(e.id)
                if node is None:
                    kind = EntityKind.from_label(e.type)
                    self._nodes[e.id] = _Node(
                        id=e.id,
                        kind=kind,
                        label=e.type,
                        index=self._alloc_index(),
                        properties=dict(e.properties),
                    )
                    self._out.setdefault(e.id, set())
                    self._in.setdefault(e.id, set())
                    self._jrec("node+", e.id, int(kind))
                else:
                    node.properties.update(e.properties)
                    self._jrec("node~", e.id)
                n += 1
            self._version += 1
        return n

    def touch_nodes(self, node_ids: Iterable[str]) -> int:
        """Journal a ``node~`` record for nodes whose property bags were
        mutated in place (the kube-state delta path,
        simulator/stream.sync_touched_to_store, updates dicts directly for
        speed and bypasses upsert): journal consumers — streaming sync()
        and the graft-shield write-ahead log — re-extract features for
        touched nodes, so in-place mutations stay recoverable too."""
        n = 0
        with self._lock:
            for nid in node_ids:
                if nid in self._nodes:
                    self._jrec("node~", nid)
                    n += 1
            if n:
                self._version += 1
        return n

    def upsert_relations(self, relations: Iterable[GraphRelation]) -> int:
        """Batch MERGE of edges (reference neo4j.py:145-166). Edges whose
        endpoints don't exist yet get placeholder nodes (MERGE semantics)."""
        n = 0
        with self._lock:
            for r in relations:
                kind = RelationKind.from_label(r.relation_type)
                for nid in (r.source_id, r.target_id):
                    if nid not in self._nodes:
                        label = nid.split(":", 1)[0].capitalize() if ":" in nid else "Container"
                        nkind = EntityKind.from_label(label)
                        self._nodes[nid] = _Node(
                            id=nid, kind=nkind, label=label,
                            index=self._alloc_index(),
                        )
                        self._out.setdefault(nid, set())
                        self._in.setdefault(nid, set())
                        self._jrec("node+", nid, int(nkind))
                key = (r.source_id, r.target_id, kind)
                edge = self._edges.get(key)
                if edge is None:
                    self._edges[key] = _Edge(r.source_id, r.target_id, kind, dict(r.properties))
                    self._out[r.source_id].add((r.target_id, kind))
                    self._in[r.target_id].add((r.source_id, kind))
                    self._jrec("edge+", r.source_id, r.target_id, int(kind))
                else:
                    edge.properties.update(r.properties)
                n += 1
            self._version += 1
        return n

    def _alloc_index(self) -> int:
        """Monotone insertion index. Never reused after removal — the index
        is a sort key only, so holes are free and removal stays O(degree)."""
        i = self._next_index
        self._next_index += 1
        return i

    def _remove_one(self, node_id: str) -> bool:
        """O(degree) unlink. Caller holds the lock and bumps the version."""
        node = self._nodes.get(node_id)
        if node is None:
            return False
        self._jrec("node-", node_id, int(node.kind))
        for dst, kind in list(self._out.get(node_id, ())):
            self._edges.pop((node_id, dst, kind), None)
            self._in[dst].discard((node_id, kind))
        for src, kind in list(self._in.get(node_id, ())):
            self._edges.pop((src, node_id, kind), None)
            self._out[src].discard((node_id, kind))
        self._out.pop(node_id, None)
        self._in.pop(node_id, None)
        del self._nodes[node_id]
        return True

    def remove_node(self, node_id: str) -> bool:
        """Remove a node and its edges in O(degree) — indices are never
        reassigned (the round-1 dense rewrite made each removal O(N):
        ~30M index writes to clean 500 incidents off a 50k-node store)."""
        with self._lock:
            ok = self._remove_one(node_id)
            if ok:
                self._version += 1
            return ok

    def remove_nodes(self, node_ids: Iterable[str]) -> int:
        """Batch removal with ONE version bump, so a sweep of unrelated
        removals invalidates the COO/snapshot caches once, not per node."""
        n = 0
        with self._lock:
            for nid in node_ids:
                if self._remove_one(nid):
                    n += 1
            if n:
                self._version += 1
        return n

    def remove_relation(self, source_id: str, target_id: str,
                        relation_type: str) -> bool:
        """Remove one edge (Cypher DELETE-relationship analog). O(1)."""
        kind = RelationKind.from_label(relation_type)
        with self._lock:
            if self._edges.pop((source_id, target_id, kind), None) is None:
                return False
            self._out[source_id].discard((target_id, kind))
            self._in[target_id].discard((source_id, kind))
            self._jrec("edge-", source_id, target_id, int(kind))
            self._version += 1
            return True

    def relations_from(self, source_id: str,
                       relation_type: str) -> list[str]:
        """Target ids of this node's outgoing edges of one type."""
        kind = RelationKind.from_label(relation_type)
        with self._lock:
            return sorted(d for d, k in self._out.get(source_id, ())
                          if k == kind)

    def cleanup_incident(self, incident_id: str) -> int:
        """Remove an incident node and its relations (reference neo4j.py:281-296)."""
        nid = incident_id if incident_id.startswith("incident:") else f"incident:{incident_id}"
        return 1 if self.remove_node(nid) else 0

    def cleanup_incidents(self, incident_ids: Iterable[str]) -> int:
        """Batch incident cleanup — one lock acquisition, one version bump."""
        nids = [i if i.startswith("incident:") else f"incident:{i}"
                for i in incident_ids]
        return self.remove_nodes(nids)

    # -- queries ----------------------------------------------------------

    @property
    def version(self) -> int:
        return self._version

    def node_count(self) -> int:
        return len(self._nodes)

    def edge_count(self) -> int:
        return len(self._edges)

    def get_node(self, node_id: str) -> Optional[dict[str, Any]]:
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                return None
            return {"id": node.id, "type": node.label, "properties": dict(node.properties)}

    def neighbors(self, node_id: str, direction: str = "both") -> list[tuple[str, str]]:
        """[(neighbor_id, relation_label)] — direction in {out,in,both}."""
        with self._lock:
            out: list[tuple[str, str]] = []
            if direction in ("out", "both"):
                out += [(d, RelationKind(k).name) for d, k in self._out.get(node_id, ())]
            if direction in ("in", "both"):
                out += [(s, RelationKind(k).name) for s, k in self._in.get(node_id, ())]
            return out

    def _undirected_coo(self) -> tuple[list[str], dict[str, int], Any, Any]:
        """Version-cached undirected COO edge index for the native BFS
        kernel, with the id→dense-row map (node .index has holes after
        removals, so rows are assigned here). Caller must hold the lock."""
        import numpy as np

        if self._coo_cache is not None and self._coo_cache[0] == self._version:
            return (self._coo_cache[1], self._coo_cache[2],
                    self._coo_cache[3], self._coo_cache[4])
        nodes = sorted(self._nodes.values(), key=lambda n: n.index)
        ids = [n.id for n in nodes]
        row = {n.id: i for i, n in enumerate(nodes)}
        m = len(self._edges)
        src = np.empty(2 * m, dtype=np.int32)
        dst = np.empty(2 * m, dtype=np.int32)
        for i, e in enumerate(self._edges.values()):
            s, d = row[e.src], row[e.dst]
            src[i], dst[i] = s, d
            src[m + i], dst[m + i] = d, s     # reverse edge: BFS is undirected
        self._coo_cache = (self._version, ids, row, src, dst)
        return ids, row, src, dst

    def get_incident_subgraph(self, incident_id: str, depth: int = 3) -> dict[str, Any]:
        """Depth-limited undirected subgraph around an incident — the
        reference's apoc.path.subgraphAll(maxLevel=depth) (neo4j.py:169-201).
        Large graphs use the native C++ BFS kernel (native/kaeg_native.cpp
        khop_reach) over a version-cached COO index; small graphs and
        toolchain-less installs use the Python BFS."""
        nid = incident_id if incident_id.startswith("incident:") else f"incident:{incident_id}"
        with self._lock:
            if nid not in self._nodes:
                return {"nodes": [], "relationships": []}
            seen = self._bfs_reach(nid, depth)
            nodes = [
                {"id": n.id, "type": n.label, "properties": dict(n.properties)}
                for n in (self._nodes[i] for i in seen)
            ]
            nodes.sort(key=lambda n: n["id"])
            rels = [
                {"source": e.src, "target": e.dst, "type": RelationKind(e.kind).name,
                 "properties": dict(e.properties)}
                for e in self._edges.values()
                if e.src in seen and e.dst in seen
            ]
            return {"nodes": nodes, "relationships": rels}

    def _bfs_reach(self, nid: str, depth: int) -> set[str]:
        """Node ids within `depth` undirected hops of `nid` (inclusive).
        Caller must hold the lock."""
        if len(self._nodes) >= self._NATIVE_BFS_MIN_NODES:
            from .. import native as _native
            if _native.available():
                ids, row, src, dst = self._undirected_coo()
                seed = row[nid]     # dense COO row, NOT .index (holes)
                reach = _native.khop_reach_native(src, dst, len(ids), seed, depth)
                if reach is not None:
                    return {ids[i] for i in reach.nonzero()[0]}
        seen = {nid}
        frontier = [nid]
        for _ in range(depth):
            nxt = []
            for cur in frontier:
                for d, _k in self._out.get(cur, ()):
                    if d not in seen:
                        seen.add(d)
                        nxt.append(d)
                for s, _k in self._in.get(cur, ()):
                    if s not in seen:
                        seen.add(s)
                        nxt.append(s)
            frontier = nxt
            if not frontier:
                break
        return seen

    def find_related_changes(
        self,
        namespace: str,
        window_start: datetime,
        window_end: datetime,
    ) -> list[dict[str, Any]]:
        """ChangeEvents in a namespace within a time window (neo4j.py:204-228)."""
        out = []
        with self._lock:
            for node in self._nodes.values():
                if node.kind != EntityKind.CHANGE_EVENT:
                    continue
                props = node.properties
                if props.get("namespace") != namespace:
                    continue
                ts = props.get("changed_at") or props.get("timestamp")
                if ts is None:
                    continue
                when = parse_iso(ts) if isinstance(ts, str) else ts
                if window_start <= when <= window_end:
                    out.append({"id": node.id, "properties": dict(props)})
        out.sort(key=lambda c: str(c["properties"].get("changed_at", "")), reverse=True)
        return out

    def find_affected_by_node(self, node_name: str) -> list[dict[str, Any]]:
        """Pods scheduled on a node plus their owning deployments/services
        (reference Pod→Deployment→Service traversal, neo4j.py:231-251)."""
        target = f"node:{node_name}" if not node_name.startswith("node:") else node_name
        results = []
        with self._lock:
            for src, kind in self._in.get(target, ()):
                if kind != RelationKind.SCHEDULED_ON:
                    continue
                pod = self._nodes.get(src)
                if pod is None:
                    continue
                owners = [
                    self._nodes[s].id for s, k in self._in.get(src, ())
                    if k == RelationKind.OWNS and s in self._nodes
                ]
                selectors = [
                    self._nodes[s].id for s, k in self._in.get(src, ())
                    if k == RelationKind.SELECTS and s in self._nodes
                ]
                results.append({
                    "pod": pod.id,
                    "owners": sorted(owners),
                    "services": sorted(selectors),
                })
        return sorted(results, key=lambda r: r["pod"])

    def get_service_dependencies(self, service_name: str) -> dict[str, list[str]]:
        """CALLS upstream/downstream of a service (neo4j.py:254-278)."""
        sid = service_name if service_name.startswith("service:") else f"service:{service_name}"
        with self._lock:
            downstream = sorted(
                d for d, k in self._out.get(sid, ()) if k == RelationKind.CALLS
            )
            upstream = sorted(
                s for s, k in self._in.get(sid, ()) if k == RelationKind.CALLS
            )
        return {"upstream": upstream, "downstream": downstream}

    def incident_ids(self) -> list[str]:
        with self._lock:
            return sorted(
                n.id for n in self._nodes.values() if n.kind == EntityKind.INCIDENT
            )

    # -- tensorization hooks (used by snapshot.py) ------------------------

    def _raw(self) -> tuple[list[_Node], list[_Edge]]:
        with self._lock:
            nodes = sorted(self._nodes.values(), key=lambda n: n.index)
            edges = list(self._edges.values())
        return nodes, edges

    # -- persistence (the Neo4j-durability analog; settings.graph_persist_path)

    def save(self, path: str) -> int:
        """Dump the graph as JSON lines (one node/edge per line) via an
        atomic rename. Returns the number of records written."""
        import json
        import os
        import tempfile

        nodes, edges = self._raw()
        d = os.path.dirname(os.path.abspath(path)) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        n = 0
        try:
            with os.fdopen(fd, "w") as f:
                for node in nodes:
                    f.write(json.dumps({
                        "t": "n", "id": node.id, "label": node.label,
                        "properties": node.properties,
                    }, default=str) + "\n")
                    n += 1
                for edge in edges:
                    f.write(json.dumps({
                        "t": "e", "src": edge.src, "dst": edge.dst,
                        "kind": edge.kind.name, "properties": edge.properties,
                    }, default=str) + "\n")
                    n += 1
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return n

    @classmethod
    def load(cls, path: str) -> "EvidenceGraphStore":
        """Rebuild a store from a save() dump (insertion order preserved,
        so node indices — and therefore snapshots — are reproducible)."""
        import json

        store = cls()
        entities: list[GraphEntity] = []
        relations: list[GraphRelation] = []
        with open(path) as f:
            for line in f:
                rec = json.loads(line)
                if rec["t"] == "n":
                    entities.append(GraphEntity(
                        id=rec["id"], type=rec["label"],
                        properties=rec["properties"]))
                else:
                    relations.append(GraphRelation(
                        source_id=rec["src"], target_id=rec["dst"],
                        relation_type=rec["kind"],
                        properties=rec["properties"]))
        store.upsert_entities(entities)
        store.upsert_relations(relations)
        return store
