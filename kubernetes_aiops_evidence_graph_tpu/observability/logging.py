"""Structured key-value logging.

The reference imports structlog everywhere but never configures it
(SURVEY.md §5 observability). Here: a stdlib-only structured logger that is
actually configured — key=value pairs, ISO timestamps, level filtering via
settings.log_level.
"""
from __future__ import annotations

import json
import logging
import sys
from typing import Any

_CONFIGURED = False


def configure(level: str = "INFO", stream=None, as_json: bool = False) -> None:
    global _CONFIGURED
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(_KVFormatter(as_json=as_json))
    root = logging.getLogger("kaeg")
    root.handlers[:] = [handler]
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    root.propagate = False
    _CONFIGURED = True


class _KVFormatter(logging.Formatter):
    def __init__(self, as_json: bool = False):
        super().__init__()
        self.as_json = as_json

    def format(self, record: logging.LogRecord) -> str:
        fields = {
            "ts": self.formatTime(record, "%Y-%m-%dT%H:%M:%S"),
            "level": record.levelname.lower(),
            "logger": record.name,
            "event": record.getMessage(),
        }
        fields.update(getattr(record, "kv", {}))
        if self.as_json:
            return json.dumps(fields, default=str)
        return " ".join(
            f'{k}={json.dumps(v, default=str) if not isinstance(v, str) else v}'
            for k, v in fields.items()
        )


class BoundLogger:
    """structlog-style bound logger: log.info("event", key=value)."""

    def __init__(self, name: str, **bound: Any):
        self._logger = logging.getLogger(f"kaeg.{name}")
        self._bound = bound

    def bind(self, **kv: Any) -> "BoundLogger":
        out = BoundLogger.__new__(BoundLogger)
        out._logger = self._logger
        out._bound = {**self._bound, **kv}
        return out

    def _log(self, level: int, event: str, **kv: Any) -> None:
        if not _CONFIGURED:
            configure()
        self._logger.log(level, event, extra={"kv": {**self._bound, **kv}})

    def debug(self, event: str, **kv: Any) -> None:
        self._log(logging.DEBUG, event, **kv)

    def info(self, event: str, **kv: Any) -> None:
        self._log(logging.INFO, event, **kv)

    def warning(self, event: str, **kv: Any) -> None:
        self._log(logging.WARNING, event, **kv)

    def error(self, event: str, **kv: Any) -> None:
        self._log(logging.ERROR, event, **kv)


def get_logger(name: str = "app", **bound: Any) -> BoundLogger:
    return BoundLogger(name, **bound)
