"""Span tracing — actually wired, unlike the reference.

The reference declares OTel deps + a Tempo endpoint but contains zero
opentelemetry imports (SURVEY.md §5). Here: a dependency-free tracer with
workflow-step and collector spans, in-memory ring buffer + JSON export, and
an optional jax.profiler bridge for the device-side RCA pass.
"""
from __future__ import annotations

import contextlib
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional


@dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    name: str
    # absolute epoch bounds (OTLP export needs wall-clock nanos); the
    # DURATION is measured on the monotonic clock — an NTP step between
    # start and end must never yield a negative span
    start_s: float
    end_s: float = 0.0
    attributes: dict[str, Any] = field(default_factory=dict)
    status: str = "ok"
    start_mono: float = 0.0
    end_mono: float = 0.0

    @property
    def duration_ms(self) -> float:
        if self.end_mono or self.start_mono:
            return (self.end_mono - self.start_mono) * 1e3
        return (self.end_s - self.start_s) * 1e3

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id, "name": self.name,
            "start_s": self.start_s, "duration_ms": self.duration_ms,
            "attributes": self.attributes, "status": self.status,
        }


class Tracer:
    def __init__(self, max_spans: int = 4096) -> None:
        self._spans: list[Span] = []
        self._lock = threading.Lock()
        self._max = max_spans
        self._tls = threading.local()
        # spans silently evicted by the ring buffer — a tracer that loses
        # data without counting it is not auditable (graft-scope)
        self.dropped = 0
        # optional on-end hook (observability/otlp.OtlpExporter.enqueue);
        # must never raise into the traced code path
        self.on_end = None

    def _current(self) -> Optional[Span]:
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    @contextlib.contextmanager
    def span(self, name: str, parent: "Span | tuple | None" = None,
             **attributes: Any) -> Iterator[Span]:
        """Open a span. ``parent`` overrides the thread-local stack with an
        explicit context — either a Span or a ``(trace_id, span_id)`` pair
        — so a workflow resumed on another thread (or launched from a
        webhook whose HTTP span is long closed) can still join its
        originating trace (graft-scope context propagation)."""
        if parent is None:
            parent = self._current()
        if isinstance(parent, tuple):
            trace_id, parent_id = parent
        elif parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = uuid.uuid4().hex[:16], None
        s = Span(
            trace_id=trace_id,
            span_id=uuid.uuid4().hex[:16],
            parent_id=parent_id,
            name=name,
            # graft-audit: allow[wall-clock] absolute epoch field for OTLP startTimeUnixNano; the duration uses start_mono
            start_s=time.time(),
            start_mono=time.monotonic(),
            attributes=attributes,
        )
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(s)
        try:
            yield s
        except Exception as exc:
            s.status = f"error:{type(exc).__name__}"
            raise
        finally:
            s.end_mono = time.monotonic()
            # derive the epoch end from the monotonic duration so the
            # exported span is internally consistent even across an NTP
            # step mid-span
            s.end_s = s.start_s + (s.end_mono - s.start_mono)
            stack.pop()
            self._record(s)

    def _record(self, s: Span) -> None:
        with self._lock:
            self._spans.append(s)
            if len(self._spans) > self._max:
                evicted = len(self._spans) - self._max
                self.dropped += evicted
                from .metrics import TRACE_SPANS_DROPPED
                TRACE_SPANS_DROPPED.inc(float(evicted), site="tracer_ring")
                self._spans = self._spans[-self._max:]
        if self.on_end is not None:
            try:
                self.on_end(s)
            except Exception:  # graft-audit: allow[broad-except] telemetry hook must never break the traced path
                pass

    def emit(self, s: Span) -> None:
        """Record a pre-timed span built outside the context-manager path
        (graft-scope materializes a tick's stage spans retrospectively at
        the fetch boundary — one emit per fetched tick, zero span objects
        in the per-stage hot path)."""
        self._record(s)

    @contextlib.contextmanager
    def attach(self, span: Span) -> Iterator[Span]:
        """Push an ALREADY-OPEN span onto this thread's context stack
        without re-timing or re-recording it: workflow steps run on
        executor threads whose stack is empty, so without this every span
        a step opens (collector spans, serving-tick spans) would start an
        unrelated trace instead of parenting under the step."""
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(span)
        try:
            yield span
        finally:
            stack.pop()

    def export(self, trace_id: str | None = None) -> list[dict]:
        with self._lock:
            return [s.to_dict() for s in self._spans
                    if trace_id is None or s.trace_id == trace_id]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


TRACER = Tracer()


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """jax.profiler bridge for the TPU scoring path: wraps a block in a
    profiler trace viewable in TensorBoard/XProf."""
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
