from .logging import BoundLogger, configure, get_logger
from .metrics import (
    ALERTS_DEDUPLICATED,
    ALERTS_RECEIVED,
    COLLECTOR_DURATION,
    EVIDENCE_COLLECTED,
    HYPOTHESES_GENERATED,
    INCIDENTS_CREATED,
    INCIDENTS_RESOLVED,
    RCA_DURATION,
    REGISTRY,
    REMEDIATION_ATTEMPTS,
    WEBHOOK_LATENCY,
    WORKFLOW_STEP_DURATION,
    WORKFLOW_STEPS,
    Counter,
    Gauge,
    Histogram,
    Registry,
)
from .tracing import TRACER, Span, Tracer, device_trace

# wire the collector hook (avoids an import cycle at package load)
from .. import observability_hooks as _hooks
from .metrics import COLLECTOR_DURATION as _cd, EVIDENCE_COLLECTED as _ec


def _observe_collector(name: str, result) -> None:
    _cd.observe(result.duration_seconds, collector=name)
    _ec.inc(len(result.evidence), collector=name)


_hooks.set_collector_observer(_observe_collector)

__all__ = [
    "BoundLogger", "configure", "get_logger",
    "REGISTRY", "Counter", "Gauge", "Histogram", "Registry",
    "ALERTS_RECEIVED", "ALERTS_DEDUPLICATED", "INCIDENTS_CREATED",
    "INCIDENTS_RESOLVED", "REMEDIATION_ATTEMPTS", "HYPOTHESES_GENERATED",
    "EVIDENCE_COLLECTED", "WEBHOOK_LATENCY", "COLLECTOR_DURATION",
    "RCA_DURATION", "WORKFLOW_STEP_DURATION", "WORKFLOW_STEPS",
    "TRACER", "Tracer", "Span", "device_trace",
]
