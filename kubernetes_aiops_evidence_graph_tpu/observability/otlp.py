"""OTLP/HTTP span export — ships the in-process tracer's spans to Tempo.

The reference declares a Tempo OTLP endpoint (docker-compose.yml:149-161,
observability/tempo/tempo.yaml) and OTel settings (settings.py:90-91) but
contains zero opentelemetry imports, so nothing ever ships. Here the
dependency-free tracer (tracing.py) gets a real exporter: spans are
enqueued on end and a daemon thread POSTs OTLP/HTTP JSON batches to
``{endpoint}/v1/traces``. Export is best-effort — a dead collector never
blocks or fails the pipeline (same degradation polarity as collectors).
"""
from __future__ import annotations

import http.client
import json
import threading
import urllib.request
from typing import Any

from .tracing import Span

_FLUSH_INTERVAL_S = 2.0
_MAX_BATCH = 512
_MAX_QUEUE = 8192


def _otlp_value(v: Any) -> dict:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def span_to_otlp(s: Span) -> dict:
    """One tracer Span -> OTLP JSON span (trace ids padded to 32 hex)."""
    return {
        "traceId": s.trace_id.zfill(32)[:32],
        "spanId": s.span_id.zfill(16)[:16],
        **({"parentSpanId": s.parent_id.zfill(16)[:16]} if s.parent_id else {}),
        "name": s.name,
        "kind": 1,  # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(int(s.start_s * 1e9)),
        "endTimeUnixNano": str(int(s.end_s * 1e9)),
        "attributes": [{"key": k, "value": _otlp_value(v)}
                       for k, v in s.attributes.items()],
        "status": ({"code": 1} if s.status == "ok"
                   else {"code": 2, "message": s.status}),
    }


class OtlpExporter:
    """Batching background exporter. Attach with ``TRACER.on_end``."""

    def __init__(self, endpoint: str, service_name: str = "kaeg-tpu",
                 flush_interval_s: float = _FLUSH_INTERVAL_S) -> None:
        self.endpoint = endpoint.rstrip("/")
        self.service_name = service_name
        self._queue: list[Span] = []
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._dropped = 0
        self._exported = 0
        self._flush_interval_s = flush_interval_s
        self.tracer = None     # set by attach(); read by stats()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="kaeg-otlp-export")
        self._thread.start()

    def attach(self, tracer) -> "OtlpExporter":
        """Wire this exporter as the tracer's on-end hook and remember the
        tracer so stats() can report ITS ring-buffer losses too — one
        stats surface for every place the telemetry path can drop data."""
        tracer.on_end = self.enqueue
        self.tracer = tracer
        return self

    # -- producer side ----------------------------------------------------

    def enqueue(self, span: Span) -> None:
        with self._lock:
            if len(self._queue) >= _MAX_QUEUE:
                self._count_dropped(1)  # bounded queue: never grow unbounded
                return                  # when the collector is down
            self._queue.append(span)
        if len(self._queue) >= _MAX_BATCH:
            self._wake.set()

    def _count_dropped(self, n: int) -> None:
        """Caller holds ``_lock``."""
        self._dropped += n
        from .metrics import TRACE_SPANS_DROPPED
        TRACE_SPANS_DROPPED.inc(float(n), site="exporter_queue")

    # -- consumer side ----------------------------------------------------

    def _loop(self) -> None:
        while not self._stop:
            self._wake.wait(self._flush_interval_s)
            self._wake.clear()
            self.flush()

    def flush(self) -> int:
        """Drain and POST one batch; returns spans shipped (0 on failure —
        the batch is dropped, not retried: traces are telemetry, and a dead
        Tempo must not grow host memory)."""
        with self._lock:
            batch, self._queue = self._queue[:_MAX_BATCH], self._queue[_MAX_BATCH:]
        if not batch:
            return 0
        body = json.dumps({
            "resourceSpans": [{
                "resource": {"attributes": [{
                    "key": "service.name",
                    "value": {"stringValue": self.service_name}}]},
                "scopeSpans": [{
                    "scope": {"name": "kaeg.tracer"},
                    "spans": [span_to_otlp(s) for s in batch],
                }],
            }],
        }).encode()
        try:
            req = urllib.request.Request(
                self.endpoint + "/v1/traces", body,
                {"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=5) as resp:
                resp.read()
            with self._lock:   # daemon flush and manual flush/close race
                self._exported += len(batch)
            return len(batch)
        except (OSError, http.client.HTTPException):
            # dead/unreachable collector: RETAIN the batch (front of the
            # queue, original order) up to the bounded-queue cap so a
            # transient outage loses nothing; beyond the cap the overflow
            # is dropped and counted. Never block or fail the traced path
            # (export stays best-effort); returning 0 is what stops the
            # close() drain loop from spinning on a dead endpoint.
            with self._lock:
                space = _MAX_QUEUE - len(self._queue)
                keep = batch[:space] if space > 0 else []
                self._queue[:0] = keep
                if len(batch) > len(keep):
                    self._count_dropped(len(batch) - len(keep))
            return 0

    def stats(self) -> dict:
        with self._lock:
            return {"queued": len(self._queue), "exported": self._exported,
                    "dropped": self._dropped,
                    # the tracer's own ring-buffer evictions, when attached:
                    # every loss site in the span path, one surface
                    "tracer_dropped": getattr(self.tracer, "dropped", 0)}

    def close(self) -> None:
        """Stop the flush thread and drain what a live collector will
        take. Idempotent, and flush() stays safe to call afterwards (a
        final manual flush after close is the shutdown idiom)."""
        self._stop = True
        self._wake.set()
        if self._thread.is_alive():
            self._thread.join(timeout=2)
        while self.flush():   # drain the whole backlog, not one batch
            pass
