"""In-process metrics registry with Prometheus text exposition.

Implements the FULL metric set the reference promised but only partially
emitted (SURVEY.md §3.6 item 7): the 5 real ones (main.py:30-48,
base.py:19-23) plus the 4 referenced-but-never-defined ones, without a
prometheus_client dependency.
"""
from __future__ import annotations

import threading
from collections import defaultdict
from typing import Iterable

_DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class _Metric:
    def __init__(self, name: str, help_: str, kind: str):
        self.name = name
        self.help = help_
        self.kind = kind
        self._lock = threading.Lock()


class Counter(_Metric):
    def __init__(self, name: str, help_: str = ""):
        super().__init__(name, help_, "counter")
        self._values: dict[tuple, float] = defaultdict(float)

    def inc(self, value: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] += value

    def value(self, **labels) -> float:
        return self._values.get(tuple(sorted(labels.items())), 0.0)

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} counter"
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items:
            yield f"{self.name}{_fmt_labels(key)} {v}"


class Gauge(Counter):
    def __init__(self, name: str, help_: str = ""):
        _Metric.__init__(self, name, help_, "gauge")
        self._values = defaultdict(float)

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[tuple(sorted(labels.items()))] = value

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} gauge"
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items:
            yield f"{self.name}{_fmt_labels(key)} {v}"


class Histogram(_Metric):
    def __init__(self, name: str, help_: str = "", buckets=_DEFAULT_BUCKETS):
        super().__init__(name, help_, "histogram")
        self.buckets = tuple(buckets)
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = defaultdict(float)
        self._totals: dict[tuple, int] = defaultdict(int)

    def observe(self, value: float, **labels) -> None:
        self.observe_key(value, tuple(sorted(labels.items())))

    def observe_key(self, value: float, key: tuple) -> None:
        """Fast path for hot callers (graft-scope per-tick stages) that
        pre-build the sorted label-tuple once instead of per observation.
        ``key`` must be ``tuple(sorted(labels.items()))``."""
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._sums[key] += value
            self._totals[key] += 1

    def time(self, **labels):
        import time as _t

        class _Timer:
            def __enter__(timer):
                timer.t0 = _t.perf_counter()
                return timer

            def __exit__(timer, *exc):
                self.observe(_t.perf_counter() - timer.t0, **labels)
                return False

        return _Timer()

    def percentile(self, q: float, **labels) -> float:
        """Approximate percentile from bucket counts with linear
        interpolation WITHIN the landing bucket (Prometheus
        histogram_quantile semantics): the old upper-bound answer
        overstated every quantile by up to a full bucket width, which at
        the SLO bucket ladder turned a 30 ms p50 into 50 ms. Quantiles
        beyond the last finite bucket clamp to its bound (there is no
        width to interpolate into +Inf)."""
        key = tuple(sorted(labels.items()))
        total = self._totals.get(key, 0)
        if not total:
            return 0.0
        target = q * total
        counts = self._counts.get(key, [])
        prev_cum = 0
        for i, c in enumerate(counts):
            if c >= target:
                lo = self.buckets[i - 1] if i else 0.0
                in_bucket = c - prev_cum
                if in_bucket <= 0:
                    return lo
                frac = (target - prev_cum) / in_bucket
                return lo + frac * (self.buckets[i] - lo)
            prev_cum = c
        return self.buckets[-1] if self.buckets else 0.0

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} histogram"
        with self._lock:
            snapshot = [
                (key, list(self._counts.get(key, [0] * len(self.buckets))),
                 self._sums[key], self._totals[key])
                for key in sorted(self._totals)
            ]
        for key, counts, total_sum, total in snapshot:
            for b, c in zip(self.buckets, counts):
                yield f'{self.name}_bucket{_fmt_labels(key, le=b)} {c}'
            yield f'{self.name}_bucket{_fmt_labels(key, le="+Inf")} {total}'
            yield f"{self.name}_sum{_fmt_labels(key)} {total_sum}"
            yield f"{self.name}_count{_fmt_labels(key)} {total}"


def _fmt_labels(key: tuple, le=None) -> str:
    parts = [f'{k}="{v}"' for k, v in key]
    if le is not None:
        parts.append(f'le="{le}"')
    return "{" + ",".join(parts) + "}" if parts else ""


class Registry:
    def __init__(self) -> None:
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            return self._metrics.setdefault(metric.name, metric)

    def counter(self, name: str, help_: str = "") -> Counter:
        return self.register(Counter(name, help_))  # type: ignore[return-value]

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self.register(Gauge(name, help_))  # type: ignore[return-value]

    def histogram(self, name: str, help_: str = "", buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self.register(Histogram(name, help_, buckets))  # type: ignore[return-value]

    def expose(self) -> str:
        lines: list[str] = []
        for m in self._metrics.values():
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


REGISTRY = Registry()

# The reference's metric surface, complete (main.py:30-48 + promised set):
ALERTS_RECEIVED = REGISTRY.counter(
    "aiops_alerts_received_total", "Alerts received by webhook")
ALERTS_DEDUPLICATED = REGISTRY.counter(
    "aiops_alerts_deduplicated_total", "Alerts dropped as duplicates")
INCIDENTS_CREATED = REGISTRY.counter(
    "aiops_incidents_created_total", "Incidents created")
INCIDENTS_RESOLVED = REGISTRY.counter(
    "aiops_incidents_resolved_total", "Incidents resolved or closed")
REMEDIATION_ATTEMPTS = REGISTRY.counter(
    "aiops_remediation_attempts_total", "Remediation executions attempted")
HYPOTHESES_GENERATED = REGISTRY.counter(
    "aiops_hypotheses_generated_total", "Hypotheses generated")
EVIDENCE_COLLECTED = REGISTRY.counter(
    "aiops_evidence_collected_total", "Evidence items collected")
WEBHOOK_LATENCY = REGISTRY.histogram(
    "aiops_webhook_latency_seconds", "Webhook handling latency")
COLLECTOR_DURATION = REGISTRY.histogram(
    "aiops_collector_duration_seconds", "Per-collector collection duration")
RCA_DURATION = REGISTRY.histogram(
    "aiops_rca_duration_seconds", "RCA scoring duration (new)")
WORKFLOW_STEP_DURATION = REGISTRY.histogram(
    "aiops_workflow_step_duration_seconds", "Workflow step duration (new)")
WORKFLOW_STEPS = REGISTRY.counter(
    "aiops_workflow_steps_total",
    "Workflow step outcomes by status (completed|failed) — feeds the "
    "WorkflowFailures alert rule")

# graft-saga instrumentation (workflow/engine.py, workflow/worker.py,
# remediation/executor.py + compensator.py): the durable exactly-once
# remediation lifecycle. Every intent/result/reconciliation, lease
# fencing event, resume, orphaned step thread, and compensation outcome
# is counted — the action trail behind a verdict must be as auditable
# as the verdict itself.
WORKFLOW_STEP_ORPHANS = REGISTRY.counter(
    "aiops_workflow_step_orphans_total",
    "Sync workflow steps whose executor THREAD outlived the step "
    "timeout (asyncio.wait_for cannot cancel a thread — the step keeps "
    "running detached while the engine retries/fails), by step")
WORKFLOW_STALLED = REGISTRY.gauge(
    "aiops_workflow_stalled",
    "Workflows currently stalled: open incidents whose journal carries "
    "a failed step or whose resume budget is exhausted — visible to the "
    "resumer sweep and GET /api/v1/workflows")
WORKFLOW_RESUMES = REGISTRY.counter(
    "aiops_workflow_resumes_total",
    "Orphaned workflows (expired lease, no failed steps) re-entered "
    "through the journal-replay path by the resumer sweep")
WORKFLOW_LEASE_FENCED = REGISTRY.counter(
    "aiops_workflow_lease_fenced_total",
    "Workflow runs aborted at a step boundary because their lease was "
    "lost (expired and reclaimed by another worker) — the fencing that "
    "keeps two workers from double-driving one workflow")
ACTION_INTENTS = REGISTRY.counter(
    "aiops_action_intents_total",
    "Two-phase execution intent rows journaled BEFORE a cluster "
    "mutation dispatch, by action_type")
ACTION_DUP_PREVENTED = REGISTRY.counter(
    "aiops_action_duplicates_prevented_total",
    "Action executions answered from the durable ledger's recorded "
    "result instead of re-firing the cluster mutation (journal-replay "
    "after a crash between the mutation and the step commit)")
ACTION_RECONCILED = REGISTRY.counter(
    "aiops_action_reconciliations_total",
    "In-doubt executions (intent without result after a crash) settled "
    "by probing cluster state, by outcome (completed = the mutation had "
    "landed; refired = the probe proved it had not)")
COMPENSATION_ACTIONS = REGISTRY.counter(
    "aiops_compensation_actions_total",
    "Saga compensation executions after a failed verification, by "
    "action_type and outcome (completed | failed | denied | noop)")
COMPENSATION_ESCALATIONS = REGISTRY.counter(
    "aiops_compensation_escalations_total",
    "Compensations that exhausted their bounded attempts (or were "
    "policy-denied) and escalated to a human via an "
    "escalate_to_human action row")

# graft-intake instrumentation (ingestion/columnar.py + the columnar
# staging path in rca/streaming.py): the webhook→staged-delta segment,
# previously the one part of the serving path with no metric surface.
INGEST_ROWS = REGISTRY.counter(
    "aiops_ingest_rows_total",
    "Webhook alert rows through the columnar ingest edge, by source and "
    "outcome (created | duplicate | not_firing | malformed)")
INGEST_ROWS_PER_SEC = REGISTRY.gauge(
    "aiops_ingest_rows_per_sec",
    "Rows/s through the most recent columnar webhook batch "
    "(batch rows / parse+normalize+dedup wall), by source")
INGEST_BATCH_FILL = REGISTRY.gauge(
    "aiops_ingest_batch_fill",
    "Fill fraction of the most recent staged buffer, by site: webhook = "
    "eligible rows / batch rows; delta = staged delta entries / the "
    "_DELTA_BUCKETS rung the packed slab was sized on")
INGEST_MALFORMED_ROWS = REGISTRY.counter(
    "aiops_ingest_malformed_rows_total",
    "Webhook rows masked as malformed (non-dict alert, non-dict labels, "
    "unparseable timestamp) — masked and counted, never a 500, by source")
INGEST_STAGE_SECONDS = REGISTRY.histogram(
    "aiops_ingest_stage_seconds",
    "Columnar ingest stage durations per webhook batch "
    "(parse | normalize | dedup | persist), by stage/source",
    buckets=_DEFAULT_BUCKETS)
INGEST_DEDUP_HITS = REGISTRY.counter(
    "aiops_ingest_dedup_hits_total",
    "Batch dedup probe hits (rows suppressed as duplicates by the "
    "fingerprint window) — with aiops_ingest_rows_total this is the "
    "dedup hit ratio, by source")
INGEST_DEDUP_EVICTIONS = REGISTRY.counter(
    "aiops_ingest_dedup_evictions_total",
    "Live fingerprints evicted from a full hashed-ring probe "
    "neighborhood before their TTL (window pressure)")
INGEST_DEDUP_OCCUPANCY = REGISTRY.gauge(
    "aiops_ingest_dedup_window_occupancy",
    "Live (unexpired) fingerprint slots resident in the hashed dedup "
    "ring")

# graft-storm instrumentation (ingestion/admission.py + the overload
# paths in app.py / rca/streaming.py / rca/shield.py): the admission
# gate, storm-mode tier, circuit breakers, and the absorb busy/backlog
# escalation — the overload story must be exactly accountable (the
# webhook_storm bench asserts admitted + shed + sampled sums match).
ADMISSION_ADMITTED = REGISTRY.counter(
    "aiops_admission_admitted_total",
    "Webhook rows admitted by the per-tenant token-bucket gate, by "
    "tenant and severity")
ADMISSION_SHED = REGISTRY.counter(
    "aiops_admission_shed_total",
    "Webhook rows shed by the admission gate (token bucket exhausted — "
    "lowest severity first, critical NEVER), by tenant and severity")
ADMISSION_TOKENS = REGISTRY.gauge(
    "aiops_admission_tokens",
    "Admission token-bucket level after the most recent batch, by "
    "tenant (negative = critical-only overdraft, bounded at -burst)")
STORM_MODE = REGISTRY.gauge(
    "aiops_storm_mode",
    "1 while the ingest path is in the hysteresis-gated storm tier "
    "(degraded: pre-shed info, sampled persistence, harder coalescing)")
STORM_TRANSITIONS = REGISTRY.counter(
    "aiops_storm_transitions_total",
    "Storm-mode tier transitions, by direction (enter | exit)")
STORM_SAMPLED_ROWS = REGISTRY.counter(
    "aiops_storm_sampled_rows_total",
    "Non-critical fresh rows suppressed by storm-mode sampled "
    "persistence (registered back into the dedup ring as presumed "
    "re-arrivals past an evicting window), by tenant")
BREAKER_STATE = REGISTRY.gauge(
    "aiops_breaker_state",
    "Circuit-breaker state by breaker name: 0 closed, 1 half_open, "
    "2 open")
BREAKER_TRANSITIONS = REGISTRY.counter(
    "aiops_breaker_transitions_total",
    "Circuit-breaker state transitions, by breaker name and new state")
PERSIST_SPILLED = REGISTRY.counter(
    "aiops_persist_spilled_total",
    "Incidents diverted to the bounded spill journal while the SQLite "
    "persist breaker was open (replayed on breaker close)")
PERSIST_SPILL_REPLAYED = REGISTRY.counter(
    "aiops_persist_spill_replayed_total",
    "Spilled incidents persisted by the post-recovery replay")
PERSIST_SPILL_DROPPED = REGISTRY.counter(
    "aiops_persist_spill_dropped_total",
    "Spilled incidents dropped because the bounded spill journal "
    "overflowed (oldest-first) — the accountable data-loss path of a "
    "wedged DB outlasting the spill capacity")
SERVE_ABSORB_BUSY = REGISTRY.counter(
    "aiops_serve_absorb_busy_total",
    "Non-blocking absorb() calls that yielded busy because a caller-"
    "boundary tick or fetch held the serving state (their deltas stay "
    "in the store journal for the contending boundary's sync)")
SERVE_ABSORB_SYNC_DRAINS = REGISTRY.counter(
    "aiops_serve_absorb_sync_drains_total",
    "absorb() busy yields that escalated to a synchronous journal drain "
    "because the unsynced store-journal backlog crossed "
    "ingest_max_journal_backlog")

# Serving-pipeline instrumentation (graft-pipeline, rca/streaming.py):
# the double-buffered executor that overlaps host delta staging with
# device ticks and defers device_get to the caller boundary.
SERVE_PIPELINE_INFLIGHT = REGISTRY.gauge(
    "aiops_serve_pipeline_inflight",
    "Dispatched-but-unfetched ticks in the serving pipeline, by pack "
    "label (graft-swell: one series per serving mesh)")
SERVE_PIPELINE_STALL_SECONDS = REGISTRY.counter(
    "aiops_serve_pipeline_stall_seconds_total",
    "Time blocked waiting for a pipeline slot after the coalescing bound "
    "(top of the delta ladder) was reached, by pack label")
SERVE_COALESCED_TICKS = REGISTRY.counter(
    "aiops_serve_coalesced_ticks_total",
    "Tick submissions whose deltas merged into a later, larger tick "
    "because the pipeline was full (backpressure without blocking)")
SERVE_COALESCED_TICK_SIZE = REGISTRY.gauge(
    "aiops_serve_coalesced_tick_size",
    "Pending delta entries carried by the most recent coalesced tick")
SERVE_DEFERRED_FETCHES = REGISTRY.counter(
    "aiops_serve_deferred_fetches_total",
    "Tick results superseded and dropped without a device->host fetch "
    "(the readback the deferred-fetch boundary avoided)")
SERVE_FETCHED_BYTES = REGISTRY.counter(
    "aiops_serve_fetched_bytes_total",
    "Bytes actually moved device->host by serving fetches, by path label")

# graft-surge instrumentation (rca/surge.py + the async workflow drive):
# cross-tenant verdict batching on one resident state. The histogram is
# the batching story in one surface — incidents scored per device pass,
# labeled by how many tenants were packed onto the state; the gauge makes
# per-tenant backpressure visible (staged-but-unticked delta entries).
SERVE_BATCH_INCIDENTS = REGISTRY.histogram(
    "aiops_serve_batch_incidents",
    "Live incidents scored by one device pass of the resident serving "
    "state, by tenants label (cross-tenant packing: N tenants' concurrent "
    "incidents ride ONE jitted pass instead of one pass per incident)",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0,
             1024.0))
SERVE_TENANT_QUEUE_DEPTH = REGISTRY.gauge(
    "aiops_serve_tenant_queue_depth",
    "Pending (staged, not yet ticked) delta entries per tenant region of "
    "the multi-tenant resident scorer, by tenant label")
SERVE_TENANT_QUARANTINES = REGISTRY.counter(
    "aiops_serve_tenant_quarantines_total",
    "Tenant regions quarantined off the shared tick (poisoned deltas or "
    "journal truncation), by tenant label — the other tenants' ticks "
    "continue while the quarantined region re-mirrors from its store")
SERVE_TENANT_REBUILDS = REGISTRY.counter(
    "aiops_serve_tenant_rebuilds_total",
    "Region-scoped tenant re-mirrors (store-derived heal staged as "
    "in-place deltas) — the per-tenant rebuild that never stalls the "
    "other tenants' ticks, by tenant label")

# graft-shield instrumentation (rca/shield.py + rca/journal.py): the
# crash-consistent recovery layer over the donated serving state. Every
# degradation-tier transition and recovery action is counted — a recovery
# path that cannot be observed cannot be trusted (auditable-RCA bar).
SHIELD_SNAPSHOTS = REGISTRY.counter(
    "aiops_shield_snapshots_total",
    "Resident-state snapshots written (atomic temp+fsync+rename)")
SHIELD_JOURNAL_BYTES = REGISTRY.counter(
    "aiops_shield_journal_bytes_total",
    "Bytes appended (fsync'd) to the write-ahead delta journal")
SHIELD_REPLAYED_DELTAS = REGISTRY.counter(
    "aiops_shield_replayed_deltas_total",
    "Store-journal records re-applied from the WAL during recovery")
SHIELD_QUARANTINED_DELTAS = REGISTRY.counter(
    "aiops_shield_quarantined_deltas_total",
    "Delta batches quarantined after producing non-finite verdicts "
    "(journaled as quarantined, re-ticked from replayed clean state)")
SHIELD_WATCHDOG_TRIPS = REGISTRY.counter(
    "aiops_shield_watchdog_trips_total",
    "Ticks that exceeded the per-tick watchdog timeout")
SHIELD_TIER_TRANSITIONS = REGISTRY.counter(
    "aiops_shield_tier_transitions_total",
    "Degradation-ladder transitions by tier label (retry, "
    "kernel_fallback, sync_depth1, journal_replay, full_rebuild, "
    "rules_fallback, ladder_rebuild)")
SHIELD_RECOVERIES = REGISTRY.counter(
    "aiops_shield_recoveries_total",
    "Recoveries completed, by mode label (journal_replay | full_rebuild)")
SHIELD_NONFINITE_VERDICTS = REGISTRY.counter(
    "aiops_shield_nonfinite_verdicts_total",
    "Verdict fetches rejected by the finite guard (NaN/inf would have "
    "been served), by path label")

# graft-heal instrumentation (rca/heal.py + the shield's mesh_heal rung):
# per-shard health, live resharding and re-expansion of the serving mesh.
MESH_SHARD_HEALTH = REGISTRY.gauge(
    "aiops_mesh_shard_health",
    "Per-shard health verdict (1 healthy, 0 classified failed / "
    "excluded), by shard label (mesh position while live, global device "
    "index once excluded)")
MESH_SHARD_FAILURES = REGISTRY.counter(
    "aiops_mesh_shard_failures_total",
    "Shard-localized faults fed into the per-position classifier, by "
    "shard label")
MESH_HEALS = REGISTRY.counter(
    "aiops_mesh_heals_total",
    "Live D→D' reshards onto a survivor mesh (the mesh_heal ladder rung)")
MESH_REEXPANSIONS = REGISTRY.counter(
    "aiops_mesh_reexpansions_total",
    "D'→D re-expansions after a successful half-open device probe")
MESH_SERVING_SHARDS = REGISTRY.gauge(
    "aiops_mesh_serving_shards",
    "Graph shards the resident serving state currently spans (1 = "
    "single-device fallback)")
MESH_ATTEST_MISMATCH = REGISTRY.counter(
    "aiops_mesh_attest_mismatch_total",
    "Per-shard attestation checksum mismatches (silent corruption "
    "localized to its shard), by shard label")
MESH_ATTEST_REPAIRS = REGISTRY.counter(
    "aiops_mesh_attest_repairs_total",
    "Attestation repair passes that re-uploaded mismatched shard blocks "
    "from the host-truth mirrors (no whole-state rebuild)")

# graft-swell instrumentation (rca/elastic.py + multi-pack SurgeServer):
# load-driven elastic meshes — scale events through the heal seams,
# fleet bin-packing and live tenant migration.
MESH_SCALE_EVENTS = REGISTRY.counter(
    "aiops_mesh_scale_events_total",
    "Load-driven D→D' reshards executed through the WAL-journaled "
    "adopt_mesh seam, by direction label (up | down)")
ELASTIC_SCALE_DECISIONS = REGISTRY.counter(
    "aiops_elastic_scale_decisions_total",
    "ElasticController hysteresis-gate firings that executed a scale "
    "event (after dwell + cooldown), by direction label")
FLEET_PACKS = REGISTRY.gauge(
    "aiops_fleet_packs",
    "Serving packs (MultiTenantScorer meshes) the fleet currently runs")
FLEET_TENANT_MIGRATIONS = REGISTRY.counter(
    "aiops_fleet_tenant_migrations_total",
    "Completed tenant migrations between serving packs (journal-cursor "
    "handoff, exactly-once)")
FLEET_TENANT_LOAD = REGISTRY.gauge(
    "aiops_fleet_tenant_load_rows_per_sec",
    "Per-tenant admitted-rows/s EWMA load estimate the bin-packer "
    "places by, by tenant label")

# graft-evolve instrumentation (learn/): the online learning loop.
# Every stage of the verdicts→checkpoint pipeline is counted — harvested
# episodes, buffer occupancy, fine-tune steps, the gate's eval accuracy,
# and the swap/rollback/gate-reject outcomes — so "the model silently got
# worse" is not a failure mode this loop can have.
LEARN_EPISODES_HARVESTED = REGISTRY.counter(
    "aiops_learn_episodes_harvested_total",
    "Labeled incidents harvested into replay-buffer episodes, by label "
    "source (feedback | verification | weak_rule)")
LEARN_BUFFER_SIZE = REGISTRY.gauge(
    "aiops_learn_buffer_size",
    "Dedup'd production episodes resident in the replay buffer")
LEARN_TRAIN_STEPS = REGISTRY.counter(
    "aiops_learn_train_steps_total",
    "Fine-tune train steps executed by the background trainer")
LEARN_EVAL_TOP1 = REGISTRY.gauge(
    "aiops_learn_eval_top1",
    "Gate holdout top-1 accuracy (simulator suite + held production "
    "slice), by params label (candidate | serving)")
LEARN_SWAPS = REGISTRY.counter(
    "aiops_learn_swaps_total",
    "Hot checkpoint swaps landed into the serving executors")
LEARN_ROLLBACKS = REGISTRY.counter(
    "aiops_learn_rollbacks_total",
    "Post-swap rollbacks to the previous params generation (nonfinite "
    "verdicts or accuracy regression after a swap)")
LEARN_GATE_REJECTS = REGISTRY.counter(
    "aiops_learn_gate_rejects_total",
    "Fine-tuned candidates discarded by the eval gate (holdout top-1 "
    "below the serving checkpoint's) — counted, never swapped")
LEARN_GENERATION = REGISTRY.gauge(
    "aiops_learn_params_generation",
    "Params generation currently serving (0 = the offline checkpoint)")

# graft-scope instrumentation (observability/scope.py): the end-to-end
# serving latency story — webhook→verdict SLO histograms, per-tick stage
# splits at the host boundaries, telemetry self-accounting (dropped
# spans), flight-recorder dumps, and roofline drift gauges.
_SLO_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.15,
                0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
WEBHOOK_VERDICT_LATENCY = REGISTRY.histogram(
    "aiops_webhook_verdict_latency_seconds",
    "End-to-end webhook→verdict latency by tenant/backend/shards — the "
    "ROADMAP item-2 SLO surface (p50/p99 via Histogram.percentile)",
    buckets=_SLO_BUCKETS)
TICK_STAGE_SECONDS = REGISTRY.histogram(
    "aiops_tick_stage_seconds",
    "Per-tick host-boundary stage durations (staging|dispatch|"
    "queue_wait|execute|fetch) by stage/backend labels",
    buckets=_SLO_BUCKETS)
TRACE_SPANS_DROPPED = REGISTRY.counter(
    "aiops_trace_spans_dropped_total",
    "Spans silently evicted by a bounded telemetry buffer, by site "
    "(tracer_ring | exporter_queue | scope_arrivals) — a tracer that "
    "cannot count its own losses is not auditable")
SCOPE_FLIGHT_DUMPS = REGISTRY.counter(
    "aiops_scope_flight_dumps_total",
    "Flight-recorder dumps written, by reason label (shield tier "
    "transitions and recoveries)")
SCOPE_FLIGHT_DUMPS_PRUNED = REGISTRY.counter(
    "aiops_scope_flight_dumps_pruned_total",
    "Old flight-recorder dump files pruned by the retention policy "
    "(settings.flight_dump_keep newest kept per directory)")
SCOPE_VERDICTS_OBSERVED = REGISTRY.counter(
    "aiops_scope_verdicts_observed_total",
    "Webhook→verdict latency samples observed, by backend label")
ROOFLINE_MODELED_BYTES = REGISTRY.gauge(
    "aiops_roofline_modeled_tick_bytes",
    "graft-cost modeled HBM bytes of the LIVE serving tick (traced at "
    "its current compiled shapes), by entrypoint and pack labels")
ROOFLINE_HALO_BYTES = REGISTRY.gauge(
    "aiops_roofline_modeled_halo_bytes",
    "graft-cost modeled collective (halo) bytes of the live serving "
    "tick, by entrypoint and pack labels")
ROOFLINE_ACHIEVED_BPS = REGISTRY.gauge(
    "aiops_roofline_achieved_bytes_per_sec",
    "Modeled tick bytes / host-observed device seconds (EWMA): the "
    "achieved-bandwidth proxy the drift gauge compares against, per "
    "(entrypoint, pack) series")
ROOFLINE_DRIFT = REGISTRY.gauge(
    "aiops_roofline_drift",
    "Achieved bytes/sec vs the session's best observed for the same "
    "(entrypoint, pack) (1.0 = at the high-water mark; a sustained fall is "
    "measured performance decaying away from the cost model without a "
    "bench run)")
