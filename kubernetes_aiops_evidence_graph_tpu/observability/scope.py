"""graft-scope: serving-path telemetry — the end-to-end latency story.

The serving stack has an async executor (graft-pipeline), a crash shield
(graft-shield) and a sharded fleet tick (graft-fleet), but until this
module nothing could attribute a verdict's latency across the pipeline:
the in-process Tracer only spanned workflow steps. graft-scope threads a
per-tick trace context through the entire hot path, with three pillars:

1. **Per-tick stage spans.** Every tick carries a :class:`TickSpan`
   recording host timestamps at the existing non-jitted boundaries —
   delta staging/packing, coalesce merges, queue wait (pipeline-full
   stalls), dispatch (the jit enqueue), device completion (the first
   host OBSERVATION of the donated tick's ready event — graft-scope
   never injects a device sync the serving path would not already pay)
   and the deferred fetch. Stage splits aggregate into the
   ``aiops_tick_stage_seconds`` histogram, and for ticks fetched under a
   live trace context they materialize as child spans of the workflow
   span — one Tempo trace shows webhook → evidence → tick → verdict.

2. **Webhook→verdict SLO.** :class:`ServeScope` stamps each incident at
   webhook arrival (monotonic) and observes the latency into
   ``aiops_webhook_verdict_latency_seconds`` (per tenant / backend /
   shard count) when its verdict materializes, carrying the webhook's
   trace context across the async worker hop so the whole workflow joins
   the webhook's trace. p50/p99 come from ``Histogram.percentile``
   (linear interpolation) — the ROADMAP item-2 SLO surface, benched by
   ``bench.py bench_webhook_verdict_slo`` under 1k ev/s churn.

3. **Flight recorder + roofline drift.** A bounded ring of the last K
   per-tick records (stage splits, coalesced size, shard routing counts,
   shield tier, nonfinite/quarantine flags) is dumped to disk on every
   shield degradation transition or recovery — turning graft-shield's
   counters into forensics. Roofline drift gauges price the LIVE tick's
   jaxpr with the graft-cost model (cached per compiled shape) and track
   modeled-bytes/observed-seconds against the session's best, so Grafana
   and CI see measured performance decaying away from the model without
   a bench run.

Hard constraints this module keeps: all timestamps are host-side
monotonic reads (the epoch anchor for OTLP export is taken ONCE from
``utils.timeutils.utcnow`` — durations never touch the wall clock, so
the ``wall-clock`` lint stays clean with zero waivers); no jitted code
is touched (COST_BASELINE unchanged); the telemetry cost is gated at
<1% of depth-2 steady-state throughput (tests/test_scope.py, marker
``perf_contract``).
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
import uuid
from typing import Any, Iterable

from ..utils.timeutils import utcnow
from . import metrics as m
from .logging import get_logger
from .tracing import TRACER, Span

log = get_logger("scope")

# one wall-clock read for the whole module: retrospectively-emitted spans
# anchor their epoch here and offset by monotonic deltas, so an NTP step
# mid-serve can never produce a negative stage span
_ANCHOR_EPOCH_S = utcnow().timestamp()
_ANCHOR_MONO = time.monotonic()


def _epoch_of(mono: float) -> float:
    return _ANCHOR_EPOCH_S + (mono - _ANCHOR_MONO)


epoch_of = _epoch_of


def emit_stage_span(name: str, stages: Iterable[tuple[str, float]],
                    **attributes: Any) -> None:
    """Retrospectively emit one span ending NOW, tiled by contiguous
    ``(stage, seconds)`` children, as a child of the calling thread's
    current span. No-op without a live trace context, so benches and
    tests driving scoring outside a trace add zero spans. Used by the
    snapshot-scoring verdict path (rca/tpu_backend.score_snapshot) whose
    timed windows must stay span-object-free."""
    parent = TRACER._current()
    if parent is None:
        return
    stages = [(s, max(float(d), 0.0)) for s, d in stages]
    now = time.monotonic()
    t0 = now - sum(d for _, d in stages)
    top = Span(trace_id=parent.trace_id, span_id=uuid.uuid4().hex[:16],
               parent_id=parent.span_id, name=name,
               start_s=_epoch_of(t0), start_mono=t0, end_mono=now,
               attributes=dict(attributes))
    top.end_s = _epoch_of(now)
    prev = t0
    for stage, dur in stages:
        t1 = prev + dur
        child = Span(trace_id=top.trace_id, span_id=uuid.uuid4().hex[:16],
                     parent_id=top.span_id, name=f"{name}.{stage}",
                     start_s=_epoch_of(prev), start_mono=prev, end_mono=t1)
        child.end_s = _epoch_of(t1)
        TRACER.emit(child)
        prev = t1
    TRACER.emit(top)


# -- per-tick trace context -------------------------------------------------

class TickSpan:
    """Host-boundary stage marks for one serving tick.

    The hot path pays one ``time.monotonic()`` read per stage mark and a
    list append — no span objects, no locks, no allocation beyond the
    marks list. Stages are CONTIGUOUS segments from ``t0``: the emitted
    child spans tile the parent tick span exactly, which is what lets a
    test pin "stage splits sum to the parent duration"."""

    __slots__ = ("tick_id", "t0", "marks", "queue_wait_s", "coalesced",
                 "pending", "shard_rows", "tier", "flags", "depth",
                 "backend", "fetched", "batch_incidents", "tenants",
                 "params_gen", "pack")

    def __init__(self, tick_id: int, backend: str, depth: int,
                 tier: str, queue_wait_s: float) -> None:
        self.tick_id = tick_id
        self.backend = backend
        self.depth = depth
        self.tier = tier
        self.queue_wait_s = queue_wait_s
        self.t0 = time.monotonic()
        self.marks: list[tuple[str, float]] = []
        self.coalesced = 0
        self.pending = 0
        self.shard_rows: tuple[int, ...] = ()
        self.flags: tuple[str, ...] = ()
        self.fetched = False
        # graft-surge: incidents scored by this tick's device pass and
        # how many tenants were packed onto the resident state — batched
        # passes must be visible in forensics, not just in the histogram
        self.batch_incidents = 0
        self.tenants = 1
        # graft-evolve: the params generation this tick dispatched
        # against (0 = the offline checkpoint) — stamped by the scorer at
        # dispatch so the flight ring shows exactly which ticks straddled
        # a hot checkpoint swap
        self.params_gen = 0
        # graft-swell: which serving pack (mesh) this tick belongs to —
        # with N packs the per-scorer gauges alias into one series unless
        # every record and gauge sample carries the pack identity
        self.pack = "0"

    def mark(self, stage: str) -> None:
        self.marks.append((stage, time.monotonic()))

    def flag(self, name: str) -> None:
        if name not in self.flags:
            self.flags = self.flags + (name,)

    def splits(self) -> dict[str, float]:
        """Contiguous stage durations in seconds; ``queue_wait`` (time
        blocked for a pipeline slot BEFORE this tick began) leads."""
        out: dict[str, float] = {}
        if self.queue_wait_s:
            out["queue_wait"] = self.queue_wait_s
        prev = self.t0
        for stage, t in self.marks:
            out[stage] = out.get(stage, 0.0) + (t - prev)
            prev = t
        return out

    def to_record(self) -> dict:
        return {
            "tick": self.tick_id,
            "backend": self.backend,
            "depth": self.depth,
            "tier": self.tier,
            "fetched": self.fetched,
            "stages_ms": {k: round(v * 1e3, 4)
                          for k, v in self.splits().items()},
            "coalesced": self.coalesced,
            "pending": self.pending,
            "shard_rows": list(self.shard_rows),
            "flags": list(self.flags),
            "batch_incidents": self.batch_incidents,
            "tenants": self.tenants,
            "params_gen": self.params_gen,
            "pack": self.pack,
            "t_epoch_s": round(_epoch_of(self.t0), 6),
        }


# -- flight recorder --------------------------------------------------------

class FlightRecorder:
    """Bounded ring of the last K tick records plus interleaved event
    records (escalations, quarantines). ``dump()`` freezes the ring to a
    JSON file — called by the shield on every degradation transition or
    recovery, so the forensic window around a fault is always on disk."""

    def __init__(self, capacity: int = 256, retention: int = 64) -> None:
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self.dumps = 0
        self.pruned = 0
        # on-disk dump retention: repeated shield transitions (heal-ladder
        # chaos is exactly that) must not grow the dump dir without bound
        # — keep the newest K per directory (settings.flight_dump_keep)
        self.retention = int(retention)
        self.last_dump: dict | None = None
        self.last_dump_path: str | None = None

    def resize(self, capacity: int) -> None:
        with self._lock:
            if self._ring.maxlen != capacity:
                self._ring = collections.deque(self._ring, maxlen=capacity)

    def set_retention(self, keep: int) -> None:
        self.retention = int(keep)

    def record(self, rec) -> None:
        """Append one record — a plain dict, or a finalized TickSpan
        (materialized to a dict lazily at snapshot/dump time: the per-tick
        hot path pays one deque append, not a dict build)."""
        with self._lock:
            self._ring.append(rec)

    def note_event(self, kind: str, **fields: Any) -> None:
        """Interleave a non-tick forensic event (shield escalation,
        quarantine, nonfinite guard) into the ring at its arrival order."""
        rec = {"event": kind, "t_epoch_s": round(_epoch_of(
            time.monotonic()), 6), **fields}
        self.record(rec)

    def snapshot(self) -> list[dict]:
        with self._lock:
            ring = list(self._ring)
        return [r.to_record() if isinstance(r, TickSpan) else r
                for r in ring]

    def dump(self, reason: str, directory: str | None = None) -> str | None:
        """Write the current ring to ``<dir>/flight_<n>_<reason>.json``;
        returns the path (None when the write failed — a full disk must
        not take the recovery path down with it)."""
        doc = {
            "reason": reason,
            "dumped_at": utcnow().isoformat(),
            "records": self.snapshot(),
        }
        with self._lock:
            self.dumps += 1
            n = self.dumps
            self.last_dump = doc
        m.SCOPE_FLIGHT_DUMPS.inc(reason=reason.split(":", 1)[0])
        d = directory or _default_flight_dir()
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in reason)[:48]
        path = os.path.join(d, f"flight_{n:04d}_{safe}.json")
        try:
            os.makedirs(d, exist_ok=True)
            with open(path, "w") as f:
                json.dump(doc, f, indent=1)
        except OSError as exc:
            log.error("flight_dump_failed", path=path, error=str(exc))
            return None
        with self._lock:
            self.last_dump_path = path
        self._prune_dumps(d)
        log.warning("flight_recorder_dumped", reason=reason, path=path,
                    records=len(doc["records"]))
        return path

    def _prune_dumps(self, directory: str) -> None:
        """Retention: keep the newest ``retention`` dump files in this
        directory (by mtime — dump numbering restarts across processes),
        remove the rest. Best-effort: a prune failure must never take
        the recovery path down."""
        keep = self.retention
        if keep <= 0:
            return
        try:
            paths = [os.path.join(directory, f)
                     for f in os.listdir(directory)
                     if f.startswith("flight_") and f.endswith(".json")]
            # mtime first (dump numbering restarts across processes),
            # name as the tiebreak (same-process dumps can land within
            # one timestamp granule)
            paths.sort(key=lambda p: (os.path.getmtime(p), p))
        except OSError:
            return
        for p in paths[:-keep] if len(paths) > keep else []:
            try:
                os.remove(p)
            except OSError:
                continue
            with self._lock:
                self.pruned += 1
            m.SCOPE_FLIGHT_DUMPS_PRUNED.inc()


def _default_flight_dir() -> str:
    from ..config import get_settings
    d = getattr(get_settings(), "scope_flight_dir", "") or ""
    return d or os.path.join(".kaeg_scope", str(os.getpid()))


FLIGHT_RECORDER = FlightRecorder()

# graft-storm: process-wide storm-mode mirror, written ONLY by
# ingestion/admission.StormMode on its hysteresis transitions (which also
# interleave a note_event into the flight ring). A plain dict read keeps
# the tick hot path allocation- and import-free: TickScope.begin stamps a
# "storm" flag onto every tick dispatched while the ingest tier is
# degraded, and rca/streaming.py reads it for the harder coalescing
# bound — serving code never imports the ingestion layer.
STORM_FLAG = {"active": False}


# -- sharded routing visibility (parallel/sharded_streaming.py hook) --------

_route_tls = threading.local()

SHARD_DELTA_ROWS = m.REGISTRY.gauge(
    "aiops_serve_shard_delta_rows",
    "Delta rows routed to each graph shard by the last routed batch "
    "(imbalance = one hot shard setting the compiled delta width for "
    "all shards)")


def note_route(shard_rows: Iterable[int]) -> None:
    """Called by the sharded delta router with the per-shard delta row
    counts of the batch it just routed: sets the imbalance gauge and
    stashes the counts (thread-local — routing and dispatch happen on the
    same serving thread) for the next tick's flight record."""
    rows = tuple(int(r) for r in shard_rows)
    _route_tls.last = rows
    for g, r in enumerate(rows):
        SHARD_DELTA_ROWS.set(float(r), shard=str(g))


def take_route() -> tuple[int, ...]:
    rows = getattr(_route_tls, "last", ())
    _route_tls.last = ()
    return rows


# -- roofline drift ---------------------------------------------------------

class _Roofline:
    """Price the LIVE tick with the graft-cost model and track achieved
    bandwidth against the session's best. Tracing is abstract
    (jax.make_jaxpr) and cached per compiled-shape key, so steady-state
    ticks pay a dict lookup; only a shape change re-traces — the same
    cadence at which XLA itself recompiles."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._costs: dict[tuple, dict] = {}
        self._tracing: set[tuple] = set()
        # graft-swell: achieved/best are PER (entrypoint, pack) — N packs
        # running the same entrypoint are distinct serving meshes whose
        # bandwidth stories must not EWMA into one series
        self._best: dict[tuple[str, str], float] = {}
        self._ewma: dict[tuple[str, str], float] = {}
        self._gauged: set[tuple[str, str]] = set()
        self._threads: list[threading.Thread] = []

    def model(self, entrypoint: str, key: tuple, fn, args,
              pack: str = "0") -> None:
        """Queue a background abstract trace of ``fn`` at ``args``'
        shapes/dtypes (one per shape key, ever). Only the avals leave the
        serving thread — captured as ShapeDtypeStructs BEFORE the real
        call consumes the donated buffers — so the serving thread pays a
        tree_map over ~7 leaves and a set lookup, never the ~ms
        make_jaxpr. Tracing (not XLA compilation) runs on a short-lived
        NON-daemon thread: exit waits out at most one in-flight trace
        instead of hard-killing it (the warm-thread lesson,
        rca/streaming.py)."""
        k = (entrypoint, key)
        with self._lock:
            rec = self._costs.get(k)
            if rec is not None:
                # cost cache hit (shape-keyed — pack-independent): the
                # only remaining work is making sure THIS pack's modeled
                # gauges exist, once, ever
                if (entrypoint, pack) in self._gauged:
                    return
                self._gauged.add((entrypoint, pack))
            elif k in self._tracing:
                return
            else:
                self._tracing.add(k)
                self._threads = [t for t in self._threads if t.is_alive()]
        if rec is not None:
            m.ROOFLINE_MODELED_BYTES.set(
                float(rec["hbm_bytes"]), entrypoint=entrypoint, pack=pack)
            m.ROOFLINE_HALO_BYTES.set(
                float(rec["collective_bytes"]), entrypoint=entrypoint,
                pack=pack)
            return
        import jax
        absargs = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), args)
        t = threading.Thread(target=self._trace_quiet,
                             args=(entrypoint, key, fn, absargs, pack),
                             name="kaeg-scope-roofline", daemon=False)
        with self._lock:
            self._threads.append(t)
        t.start()

    def _trace_quiet(self, entrypoint: str, key: tuple, fn, absargs,
                     pack: str = "0") -> None:
        try:
            import jax
            from ..analysis.cost_model import cost_jaxpr
            cost = cost_jaxpr(entrypoint, jax.make_jaxpr(fn)(*absargs))
            rec = {"hbm_bytes": int(cost.hbm_bytes),
                   "collective_bytes": int(cost.collective_bytes)}
        except (TypeError, ValueError, RuntimeError, KeyError,
                AttributeError, NotImplementedError) as exc:
            # advisory gauge: a trace failure must never surface into the
            # tick it describes — record a zero-cost sentinel so the
            # failure is visible (modeled bytes 0 ⇒ no drift signal) and
            # not retried every tick
            log.warning("roofline_trace_failed", entrypoint=entrypoint,
                        error=str(exc))
            rec = {"hbm_bytes": 0, "collective_bytes": 0}
        with self._lock:
            self._costs[(entrypoint, key)] = rec
            self._tracing.discard((entrypoint, key))
            self._gauged.add((entrypoint, pack))
        m.ROOFLINE_MODELED_BYTES.set(
            float(rec["hbm_bytes"]), entrypoint=entrypoint, pack=pack)
        m.ROOFLINE_HALO_BYTES.set(
            float(rec["collective_bytes"]), entrypoint=entrypoint,
            pack=pack)

    def join(self) -> None:
        """Wait for in-flight traces (tests and the bench's record path —
        never the serving path)."""
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            if t.is_alive():
                t.join()

    def observe(self, entrypoint: str, key: tuple, seconds: float,
                pack: str = "0") -> None:
        """Host-observed device window of one tick → achieved-bandwidth
        proxy (modeled bytes / seconds, EWMA-smoothed) and drift vs the
        session high-water mark — per (entrypoint, pack) series."""
        if seconds <= 0:
            return
        with self._lock:
            rec = self._costs.get((entrypoint, key))
        if not rec or not rec["hbm_bytes"]:
            return
        bps = rec["hbm_bytes"] / seconds
        series = (entrypoint, pack)
        with self._lock:
            prev = self._ewma.get(series)
            ewma = bps if prev is None else 0.9 * prev + 0.1 * bps
            self._ewma[series] = ewma
            best = max(self._best.get(series, 0.0), ewma)
            self._best[series] = best
        m.ROOFLINE_ACHIEVED_BPS.set(ewma, entrypoint=entrypoint, pack=pack)
        m.ROOFLINE_DRIFT.set(ewma / best if best else 0.0,
                             entrypoint=entrypoint, pack=pack)

    def achieved(self, entrypoint: str, pack: str = "0") -> float:
        """EWMA achieved-bytes/s for one (entrypoint, pack) series (0.0
        until the first observed tick) — the ElasticController's roofline
        input, read without touching the gauge registry."""
        with self._lock:
            return self._ewma.get((entrypoint, pack), 0.0)

    def best(self, entrypoint: str, pack: str = "0") -> float:
        """Session high-water achieved-bytes/s for one series (0.0 until
        the first observed tick) — the denominator of the drift signal
        the ElasticController treats as its roofline ceiling proxy."""
        with self._lock:
            return self._best.get((entrypoint, pack), 0.0)


ROOFLINE = _Roofline()


# -- the per-scorer telemetry front-end -------------------------------------

class TickScope:
    """One per resident scorer. ``begin()`` returns the tick's
    :class:`TickSpan` (or None when telemetry is off — the hot path then
    costs exactly one attribute read per boundary), ``finalize()`` folds
    it into the flight recorder + stage histograms and, when the calling
    thread carries a live trace context, emits the tick and its stage
    children as spans of that trace."""

    def __init__(self, backend: str, settings=None,
                 pack: str = "0") -> None:
        if settings is None:
            from ..config import get_settings
            settings = get_settings()
        self.enabled = bool(getattr(settings, "scope_telemetry", True))
        self.backend = backend
        # graft-swell: the owning serving pack's id — stamped onto every
        # TickSpan so multi-mesh flight records stay attributable
        self.pack = str(pack)
        self._serial = 0
        self._pending_queue_wait = 0.0
        self._stage_keys: dict[str, tuple] = {}
        FLIGHT_RECORDER.resize(
            int(getattr(settings, "scope_flight_records", 256)))
        FLIGHT_RECORDER.set_retention(
            int(getattr(settings, "flight_dump_keep", 64)))

    def _stage_key(self, stage: str) -> tuple:
        k = self._stage_keys.get(stage)
        if k is None:
            # must equal tuple(sorted({"backend":…, "stage":…}.items()))
            k = self._stage_keys[stage] = (("backend", self.backend),
                                           ("stage", stage))
        return k

    # hot-path producers ---------------------------------------------------

    def begin(self, scorer) -> TickSpan | None:
        if not self.enabled:
            return None
        self._serial += 1
        qw, self._pending_queue_wait = self._pending_queue_wait, 0.0
        span = TickSpan(self._serial, self.backend,
                        int(getattr(scorer, "pipeline_depth", 1)),
                        str(getattr(scorer, "_scope_tier", "steady")), qw)
        span.pack = self.pack
        if STORM_FLAG["active"]:
            span.flag("storm")
        return span

    def note_queue_wait(self, seconds: float) -> None:
        """A pipeline-full stall (tick_async) or pre-dispatch drain
        (rescore) belongs to the NEXT dispatched tick's record."""
        if self.enabled:
            self._pending_queue_wait += seconds

    def note_coalesced(self, pending: int) -> None:
        """A submission whose deltas merged into a later tick: recorded as
        its own flight entry (the later tick's ``coalesced`` count tells
        the same story from the dispatch side)."""
        if not self.enabled:
            return
        FLIGHT_RECORDER.record({
            "event": "coalesced", "backend": self.backend,
            "pack": self.pack, "pending": int(pending),
            "t_epoch_s": round(_epoch_of(time.monotonic()), 6)})

    # retirement -----------------------------------------------------------

    def finalize(self, span: TickSpan | None, fetched: bool = False) -> None:
        """Retire one tick into the flight ring. FETCHED ticks — the
        caller boundary, whose latency a caller actually saw — also feed
        the stage histograms and (under a live trace context) the span
        emission; superseded ticks keep their full stage story in the
        ring only, so the per-submission hot path stays a handful of
        appends (the <1% overhead contract)."""
        if span is None:
            return
        span.fetched = fetched
        if not span.shard_rows:
            span.shard_rows = take_route()
        FLIGHT_RECORDER.record(span)
        if not fetched:
            return
        for stage, dur in span.splits().items():
            m.TICK_STAGE_SECONDS.observe_key(dur, self._stage_key(stage))
        parent = TRACER._current()
        if parent is not None:
            self._emit_trace(span, parent)

    def _emit_trace(self, span: TickSpan, parent: Span) -> None:
        """Materialize the tick + its contiguous stage children as spans
        of the caller's trace. Runs once per FETCHED tick at the caller
        boundary — never in the per-stage hot path."""
        t_begin = span.t0 - span.queue_wait_s
        t_end = span.marks[-1][1] if span.marks else span.t0
        tick_span = Span(
            trace_id=parent.trace_id, span_id=uuid.uuid4().hex[:16],
            parent_id=parent.span_id, name="serve.tick",
            start_s=_epoch_of(t_begin), start_mono=t_begin,
            end_mono=t_end,
            attributes={"backend": span.backend, "tick": span.tick_id,
                        "depth": span.depth, "tier": span.tier,
                        "coalesced": span.coalesced,
                        "shard_rows": ",".join(map(str, span.shard_rows)),
                        "flags": ",".join(span.flags)})
        tick_span.end_s = _epoch_of(t_end)
        segments = []
        if span.queue_wait_s:
            segments.append(("queue_wait", t_begin, span.t0))
        prev = span.t0
        for stage, t in span.marks:
            segments.append((stage, prev, t))
            prev = t
        for stage, s0, s1 in segments:
            child = Span(
                trace_id=tick_span.trace_id,
                span_id=uuid.uuid4().hex[:16],
                parent_id=tick_span.span_id, name=f"tick.{stage}",
                start_s=_epoch_of(s0), start_mono=s0, end_mono=s1)
            child.end_s = _epoch_of(s1)
            TRACER.emit(child)
        TRACER.emit(tick_span)


# -- webhook→verdict SLO ----------------------------------------------------

class ServeScope:
    """Process-wide webhook→verdict correlation: bounded arrival registry
    keyed by incident id, each entry carrying the arrival's monotonic
    timestamp, tenant label, and the webhook span's trace context (so the
    async workflow joins the webhook's trace)."""

    _CAP = 4096

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._arrivals: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        self.dropped = 0

    def webhook_received(self, incident_id: str,
                         tenant: str = "default") -> None:
        cur = TRACER._current()
        rec = {"t": time.monotonic(), "tenant": str(tenant),
               "trace": (cur.trace_id, cur.span_id) if cur else None}
        with self._lock:
            self._arrivals[str(incident_id)] = rec
            while len(self._arrivals) > self._CAP:
                self._arrivals.popitem(last=False)
                self.dropped += 1
                m.TRACE_SPANS_DROPPED.inc(site="scope_arrivals")

    def trace_parent(self, workflow_id: str) -> tuple | None:
        """(trace_id, span_id) of the webhook that created this workflow's
        incident, if it is still registered — workflow ids are
        ``incident-<uuid>`` (workflow/incident_workflow.py)."""
        iid = workflow_id[len("incident-"):] \
            if workflow_id.startswith("incident-") else workflow_id
        with self._lock:
            rec = self._arrivals.get(iid)
        return rec["trace"] if rec else None

    def verdict_served(self, incident_id: str, backend: str = "rules",
                       shards: int = 1) -> float | None:
        """Observe one webhook→verdict latency sample; returns the latency
        (None when the incident never passed through a webhook — e.g.
        simulator-injected incidents outside the SLO window)."""
        with self._lock:
            rec = self._arrivals.pop(str(incident_id), None)
        if rec is None:
            return None
        lat = time.monotonic() - rec["t"]
        m.WEBHOOK_VERDICT_LATENCY.observe(
            lat, tenant=rec["tenant"], backend=backend, shards=str(shards))
        m.SCOPE_VERDICTS_OBSERVED.inc(backend=backend)
        return lat

    def pending(self) -> int:
        with self._lock:
            return len(self._arrivals)

    def clear(self) -> None:
        with self._lock:
            self._arrivals.clear()


SCOPE = ServeScope()
