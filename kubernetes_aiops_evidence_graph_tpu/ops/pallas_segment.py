"""Pallas TPU kernel for the relation-bucketed message-passing hot path.

BENCH round 5 measured the 50k-node GNN forward at 7.8% of its bandwidth
roofline (41.0 ms/forward, 49.6 GB/s achieved on a 635.8 GB/s part) while
the rules scan on the same chip hit 91%. The gap is the XLA lowering of
``ops.gather_matmul_segment``: the per-slice ``[E_r, H]`` gather rows and
the per-edge scatter-adds both stream through HBM at random-row
efficiency, and the ``[E_r, H]`` message table is materialized to HBM
between the matmul and the segment-sum.

``pallas_gather_matmul_segment`` maps the SAME math (fused gather →
per-relation ``[H, H]`` matmul → dst-segment accumulation over the static
relation-bucketed edge layout) onto one tiled, VMEM-resident pipeline:

* the node table ``h`` and the ``[N, K]`` accumulator live in VMEM for the
  whole pass (the PR 1 layout shrank the gather table 9.4x to ``[Pn, H]``
  — 16 MB at the bench config, small enough to sit next to the compute);
  the accumulator is seeded from a host-side zeros input via
  ``input_output_aliases`` so the kernel contains no init branch;
* the grid streams ``EDGE_TILE``-row tiles of ``(src, dst, mask)`` — one
  relation per tile, the per-tile relation id arrives via scalar prefetch
  from a static table derived from ``rel_offsets``;
* each tile gathers its source rows into a VMEM scratch, runs ONE
  ``[EDGE_TILE, H] × [H, K]`` matmul on the MXU (compute-dtype operands,
  f32 accumulation via ``preferred_element_type``) into a second scratch,
  and accumulates the message rows into the resident accumulator —
  per-edge ``+=`` against VMEM, never a per-edge HBM scatter-add, and no
  ``[E_r, H]`` message table ever exists outside the tile.

The per-edge accumulate applies updates in exact edge order — the same
left-fold the XLA kernel's scatter-add performs — so the kernel is
BIT-IDENTICAL to ``gather_matmul_segment`` on CPU (``interpret=True``),
which is the parity contract tier-1 pins (tests/test_ops.py,
tests/test_gnn_bucketed.py). Coalescing a sorted run into a register and
flushing once was evaluated and rejected: it reassociates the fold
whenever a dst recurs across relation slices, trading the bit-parity
oracle for a micro-optimization the VMEM accumulator already made cheap.
The sorted-by-``(rel, dst)`` layout (PR 1) still matters: it makes the
accumulator walk mostly-sequential rows, so ``slices_sorted`` is kept in
the signature for dispatch symmetry with the XLA kernel.

House style follows ``experiments/pallas_rules.py``: static tables built
host-side, ``interpret=True`` on CPU (auto-detected when not forced) so
tier-1 stays hermetic, bit-parity tests against the XLA kernel.

graft-fuse extends this module in two directions:

* **A real backward pass.** ``pallas_gather_matmul_segment`` now carries a
  ``custom_vjp`` whose backward IS the transposed segment layout: the
  cotangent table is gathered at ``dst`` and dst-bucket-scattered at
  ``src`` through the SAME tiled forward kernel with ``w_rel``
  transposed (``dh``), while ``dw_rel`` accumulates per-relation
  ``[H, K]`` grad matmuls (one ``[EDGE_TILE, H]ᵀ × [EDGE_TILE, K]`` MXU
  matmul per tile, f32 accumulation into a VMEM-resident ``[R, H, K]``
  accumulator seeded via input/output aliasing). Gradients flow to ``h``
  and ``w_rel`` only — ``mask`` (and the int index arrays) are treated
  as constants of the layout, which is exact for the 0/1 masks every
  caller passes; a caller differentiating w.r.t. a fractional mask must
  use the XLA kernel. Training and the online fine-tune
  (``settings.learn_pallas_grads``) can therefore leave the XLA oracle;
  the A/B parity suite pins the grads against ``jax.grad`` of the XLA
  reference (tests/test_ops.py).

* **The fused streaming tick** (``pallas_fused_gnn_tick``, behind
  ``settings.gnn_fused_tick``): ONE ``pallas_call`` from delta-scatter
  to verdict — the staged int32 delta slab scatters into the
  VMEM-resident node/edge mirrors (aliased inputs→outputs, exactly the
  donated resident state), the relation-bucketed message pass runs as
  EDGE_TILE sweeps against the resident tables, and the score reduction
  (incident readout → logits → softmax) happens in-kernel — so the
  ``[N, H]`` activations never round-trip through HBM between the
  scatter, message-pass and scoring stages the composed
  ``_gnn_tick`` pays per tick. Bit-identical to the composed
  scatter→``pallas_gather_matmul_segment``→score path (per-tile matmuls
  and per-edge accumulation replay the identical fold). Its
  ``custom_vjp`` rematerializes the composed forward over the
  differentiable Pallas gms above, so the fused tier is trainable too.

graft-tide adds the beyond-VMEM exits the fused tick deferred:

* **The DMA streaming tick** (``pallas_fused_gnn_tick_dma``, behind
  ``settings.gnn_tick_dma``): the same tick for graphs whose mirrors +
  activations outgrow VMEM. The node-feature table, the relation-
  bucketed edge mirror and BOTH ``[N, H]`` activation buffers stay
  HBM-resident (``memory_space=pltpu.ANY``); the kernel streams
  EDGE_TILE-aligned ``(src, dst, mask)`` blocks through a
  double-buffered VMEM window — ``pltpu.make_async_copy`` prefetch of
  tile ``t+1`` overlapping compute of tile ``t`` — gathers source rows
  and applies the per-destination accumulate through row-granular DMAs
  against the HBM accumulator, in the SAME edge order as the resident
  kernel, so the f32 path stays BIT-identical to the composed oracle.
  Node-granular phases (embed, per-layer update) move ``node_block``-row
  blocks through VMEM staging. Only small per-node vectors (kind, nmask,
  degree) and the per-tile windows are VMEM-resident: the VMEM floor is
  ~12 B/node + O(node_block·H), which carries 500k+ pods where the
  resident tick's ``fused_tick_vmem_bytes`` demand exceeds
  ``_VMEM_HARD_LIMIT``. Serving-only: no ``custom_vjp`` (training at
  beyond-VMEM scale would need cross-block checkpointing — the resident
  tiers stay the trainable ones).

* **bf16 compute + quantized node-feature tiers**: both fused ticks
  accept ``compute_dtype="bfloat16"`` (bf16 matmul operands, f32
  accumulation via ``preferred_element_type`` — parity-gated against
  the f32 tick like the bf16 gms kernel), and the DMA tick additionally
  accepts a bf16 or per-column-scale int8 node-feature table
  (``quantize_features``) that dequantizes block-by-block during the
  embed stream — f32 accumulate, tolerance-suite parity, and 2–4x less
  HBM feature traffic per tick.
"""
from __future__ import annotations

from functools import lru_cache, partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Edge rows per grid step. 64 divides every REL_SLICE_BUCKETS capacity
# (powers of two >= 64, then 8192-multiples — graph/snapshot.py), so tiles
# never straddle a relation slice and the per-tile relation id is a static
# table. [64, H] keeps the MXU tile busy at H = 64 while the gather loop —
# the true bottleneck — stays row-granular either way. The value lives in
# the declared ladder registry (analysis/ladders.py), where the
# ladder-divisibility check pins that it divides every rel-slice rung AND
# the above-ladder rounding step.
from ..analysis.ladders import EDGE_TILE


@lru_cache(maxsize=64)
def _tile_rel_ids(rel_offsets: tuple[int, ...]) -> np.ndarray:
    """Static per-tile relation ids: tile ``t`` covers edge rows
    ``[t*EDGE_TILE, (t+1)*EDGE_TILE)`` and belongs to exactly one relation
    slice (capacities are EDGE_TILE-aligned — checked by the caller)."""
    rels: list[int] = []
    for r in range(len(rel_offsets) - 1):
        cap = int(rel_offsets[r + 1]) - int(rel_offsets[r])
        rels.extend([r] * (cap // EDGE_TILE))
    return np.asarray(rels, np.int32)


def tiles_align(rel_offsets) -> bool:
    """Whether every relation slice capacity is a multiple of EDGE_TILE
    (true for any layout drawn from the REL_SLICE_BUCKETS ladder). The
    dispatcher falls back to the XLA kernel otherwise."""
    return all((int(hi) - int(lo)) % EDGE_TILE == 0
               for lo, hi in zip(rel_offsets[:-1], rel_offsets[1:]))


def _gms_kernel(rel_ref, acc_init_ref, h_ref, w_ref, src_ref, dst_ref,
                mask_ref, out_ref, gath_ref, msg_ref):
    """One edge tile: gather rows into VMEM scratch, one MXU matmul into
    the message scratch, per-edge accumulate into the VMEM-resident
    [N, K] output (seeded from acc_init via input/output aliasing —
    ``acc_init_ref`` is never read here)."""
    t = pl.program_id(0)

    # gather this tile's source rows (masked: padding rows contribute
    # exact zeros, matching the XLA kernel's mask-then-matmul)
    def gather_row(e, _):
        srow = src_ref[0, e]
        gath_ref[e, :] = h_ref[srow, :] * mask_ref[0, e]
        return 0

    jax.lax.fori_loop(0, EDGE_TILE, gather_row, 0)

    rel = rel_ref[t]
    msg_ref[:] = jnp.dot(gath_ref[:], w_ref[rel],
                         preferred_element_type=out_ref.dtype)

    # per-edge accumulate against VMEM, in exact edge order — the same
    # left-fold as the XLA scatter-add, hence bit-parity
    def accum_row(e, _):
        d = dst_ref[0, e]
        out_ref[d, :] = out_ref[d, :] + msg_ref[e, :]
        return 0

    jax.lax.fori_loop(0, EDGE_TILE, accum_row, 0)


def pallas_gather_matmul_segment(
    h: jax.Array,              # [N, H] node table
    w_rel: jax.Array,          # [R, H, K] per-relation transforms
    src: jax.Array,            # [E] source index, relation-bucketed layout
    dst: jax.Array,            # [E] destination/segment index
    mask: jax.Array,           # [E] 1.0 live / 0.0 padding
    rel_offsets: tuple[int, ...],   # [R+1] STATIC slice bounds into E
    num_segments: int,
    *,
    slices_sorted: bool = False,
    compute_dtype=None,
    interpret: bool | None = None,
) -> jax.Array:
    """Drop-in Pallas replacement for :func:`ops.segment.gather_matmul_segment`
    (same signature, same semantics, bit-identical results — see the
    module docstring for the tiling scheme). ``slices_sorted`` does not
    change the math here (the VMEM accumulate is order-exact either way);
    it is accepted so dispatch sites key both kernels identically.
    ``interpret=None`` auto-selects interpret mode off-TPU so tier-1 CPU
    tests exercise the kernel hermetically.

    Differentiable w.r.t. ``h`` and ``w_rel`` (graft-fuse): the attached
    ``custom_vjp`` runs the transposed-layout Pallas backward (module
    docstring). ``mask`` is treated as a layout constant (zero
    cotangent) — exact for 0/1 masks."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out_dtype = h.dtype
    k = w_rel.shape[-1]
    offs = tuple(int(o) for o in rel_offsets)
    e_total = offs[-1] if offs else 0
    if e_total == 0:
        return jnp.zeros((num_segments, k), out_dtype)
    if not tiles_align(offs):
        # a layout off the EDGE_TILE-aligned ladder (hand-built tests,
        # exotic configs): the XLA kernel handles any static slicing
        from .segment import gather_matmul_segment
        return gather_matmul_segment(
            h, w_rel, src, dst, mask, offs, num_segments,
            slices_sorted=slices_sorted, compute_dtype=compute_dtype)
    cdt = None if compute_dtype is None else jnp.dtype(compute_dtype).name
    return _gms_vjp(offs, int(num_segments), bool(interpret), cdt,
                    h, w_rel, src, dst, mask)


def _gms_forward(offs, num_segments, interpret, compute_dtype,
                 h, w_rel, src, dst, mask) -> jax.Array:
    """The tiled kernel invocation (EDGE_TILE-aligned layouts only —
    callers have already routed empty/unaligned layouts elsewhere)."""
    out_dtype = h.dtype
    k = w_rel.shape[-1]
    e_total = offs[-1]
    if compute_dtype is not None:
        # cast ONCE before the kernel, exactly like the XLA kernel: the
        # gathered rows then move at compute-dtype width and the matmul
        # still accumulates into out_dtype via preferred_element_type
        h = h.astype(compute_dtype)
        w_rel = w_rel.astype(compute_dtype)
        mask = mask.astype(compute_dtype)
    num_tiles = e_total // EDGE_TILE
    rel_ids = jnp.asarray(_tile_rel_ids(offs))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(num_tiles,),
        in_specs=[
            # accumulator seed (aliased to the output below) + node table
            # + per-relation weights: VMEM-resident for the whole pass
            # (constant index maps, so the blocks persist across grid
            # steps instead of re-streaming from HBM)
            pl.BlockSpec((num_segments, k), lambda t, rel_ref: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(h.shape, lambda t, rel_ref: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(w_rel.shape, lambda t, rel_ref: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            # edge tiles stream through: one (1, EDGE_TILE) block per step
            pl.BlockSpec((1, EDGE_TILE), lambda t, rel_ref: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, EDGE_TILE), lambda t, rel_ref: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, EDGE_TILE), lambda t, rel_ref: (t, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((num_segments, k),
                               lambda t, rel_ref: (0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((EDGE_TILE, h.shape[1]), h.dtype),  # gathered rows
            pltpu.VMEM((EDGE_TILE, k), out_dtype),     # message tile (f32)
        ],
    )
    # the zeros seed aliases the output: the accumulator starts zeroed
    # without any in-kernel init branch, and XLA can reuse the buffer
    # in place (alias indices count the scalar-prefetch operand, so the
    # seed — second overall operand — is index 1)
    return pl.pallas_call(
        _gms_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_segments, k), out_dtype),
        input_output_aliases={1: 0},
        interpret=interpret,
    )(rel_ids, jnp.zeros((num_segments, k), out_dtype), h, w_rel,
      jnp.reshape(src, (num_tiles, EDGE_TILE)),
      jnp.reshape(dst, (num_tiles, EDGE_TILE)),
      jnp.reshape(mask, (num_tiles, EDGE_TILE)))


# -- custom_vjp: the transposed segment layout (graft-fuse) ----------------

def _grad_w_kernel(rel_ref, dw_init_ref, h_ref, g_ref, src_ref, dst_ref,
                   mask_ref, dw_ref, gath_ref, ct_ref):
    """One edge tile of the ``w_rel`` backward: gather the (masked)
    source rows and the cotangent rows, one ``[H, EDGE_TILE] ×
    [EDGE_TILE, K]`` MXU matmul, accumulate into the VMEM-resident
    ``[R, H, K]`` grad table (seeded via input/output aliasing —
    ``dw_init_ref`` is never read here). f32 accumulation regardless of
    the compute dtype, the same discipline as the forward tile matmul."""
    t = pl.program_id(0)

    def gather_row(e, _):
        gath_ref[e, :] = h_ref[src_ref[0, e], :] * mask_ref[0, e]
        ct_ref[e, :] = g_ref[dst_ref[0, e], :]
        return 0

    jax.lax.fori_loop(0, EDGE_TILE, gather_row, 0)

    rel = rel_ref[t]
    dw_ref[rel] = dw_ref[rel] + jnp.dot(
        gath_ref[:].T, ct_ref[:], preferred_element_type=dw_ref.dtype)


def _gms_grad_w(offs, interpret, compute_dtype, h, g, src, dst, mask,
                w_dtype, num_rels: int) -> jax.Array:
    """[R, H, K] per-relation weight grads over the bucketed layout:
    ``dw_r = Σ_{e ∈ slice r} (h[src_e]·mask_e)ᵀ ⊗ g[dst_e]``.
    ``num_rels`` is the FULL relation-table depth (``w_rel.shape[0]``) —
    it may exceed the layout's slice count, in which case the surplus
    relations correctly get zero grads."""
    e_total = offs[-1]
    if compute_dtype is not None:
        # the forward computed messages from compute-dtype operands; the
        # gathered rows re-materialize at the same width (cotangents stay
        # f32 — grads accumulate at full precision)
        h = h.astype(compute_dtype)
        mask = mask.astype(compute_dtype)
    num_tiles = e_total // EDGE_TILE
    rel_ids = jnp.asarray(_tile_rel_ids(offs))
    hidden, k = h.shape[1], g.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(num_tiles,),
        in_specs=[
            pl.BlockSpec((num_rels, hidden, k), lambda t, rel_ref: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(h.shape, lambda t, rel_ref: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(g.shape, lambda t, rel_ref: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, EDGE_TILE), lambda t, rel_ref: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, EDGE_TILE), lambda t, rel_ref: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, EDGE_TILE), lambda t, rel_ref: (t, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((num_rels, hidden, k),
                               lambda t, rel_ref: (0, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((EDGE_TILE, hidden), h.dtype),   # gathered rows
            pltpu.VMEM((EDGE_TILE, k), g.dtype),        # cotangent rows
        ],
    )
    return pl.pallas_call(
        _grad_w_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_rels, hidden, k), w_dtype),
        input_output_aliases={1: 0},
        interpret=interpret,
    )(rel_ids, jnp.zeros((num_rels, hidden, k), w_dtype), h, g,
      jnp.reshape(src, (num_tiles, EDGE_TILE)),
      jnp.reshape(dst, (num_tiles, EDGE_TILE)),
      jnp.reshape(mask, (num_tiles, EDGE_TILE)))


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _gms_vjp(offs, num_segments, interpret, compute_dtype,
             h, w_rel, src, dst, mask):
    return _gms_forward(offs, num_segments, interpret, compute_dtype,
                        h, w_rel, src, dst, mask)


def _gms_vjp_fwd(offs, num_segments, interpret, compute_dtype,
                 h, w_rel, src, dst, mask):
    out = _gms_forward(offs, num_segments, interpret, compute_dtype,
                       h, w_rel, src, dst, mask)
    return out, (h, w_rel, src, dst, mask)


def _gms_vjp_bwd(offs, num_segments, interpret, compute_dtype, res, g):
    """The backward IS the transposed segment layout: ``dh`` re-runs the
    forward kernel with the cotangent table gathered at ``dst``,
    scattered at ``src`` and ``w_rel`` transposed (a dst-bucketed
    scatter of cotangents over the same static slices); ``dw_rel`` is
    the per-relation grad-matmul kernel above. Index arrays and ``mask``
    get zero cotangents (mask is a 0/1 layout constant — scalar
    multiplication commutes exactly through the matmul for 0/1, so the
    h/w grads match the XLA kernel's within f32 reassociation
    tolerance)."""
    h, w_rel, src, dst, mask = res
    w_t = jnp.swapaxes(w_rel, -1, -2)            # [R, K, H]
    dh = _gms_forward(offs, h.shape[0], interpret, compute_dtype,
                      g, w_t, dst, src, mask)
    dw = _gms_grad_w(offs, interpret, compute_dtype, h, g, src, dst, mask,
                     w_rel.dtype, int(w_rel.shape[0]))
    return dh, dw, None, None, None


_gms_vjp.defvjp(_gms_vjp_fwd, _gms_vjp_bwd)


# -- fused streaming tick: delta-scatter -> message pass -> verdict --------

def _fused_kernel_factory(num_layers: int, pk: int, ek: int, pi: int,
                          pn: int, pe: int, num_tiles: int,
                          compute_dtype=None):
    """Build the fused-tick kernel body for a static (layers, delta,
    incident, node, edge) shape set. One kernel invocation (no grid —
    the tile sweep is an in-kernel ``fori_loop``, so the cost model's
    scan weighting prices each phase exactly once): phase 1 scatters the
    packed delta into the VMEM-resident mirrors (the aliased outputs,
    which arrive holding the pre-tick resident state), phase 2 embeds +
    runs ``num_layers`` relation-bucketed EDGE_TILE sweeps against the
    resident activations (the per-tile matmul and per-edge accumulate
    replay ``_gms_kernel``'s exact fold — bit-parity with the composed
    scatter→gms→score path), phase 3 reduces the incident readout to
    logits/probs in-kernel. The ``[N, H]`` activations live in VMEM
    scratch for the whole tick — they never exist as an HBM buffer
    between stages, which is the modeled bytes/tick floor this kernel
    exists to lower.

    ``compute_dtype`` (graft-tide, e.g. "bfloat16") casts MATMUL OPERANDS
    only — every accumulation (agg, deg, residual adds, softmax) stays
    f32 via ``preferred_element_type``, the same discipline the bf16 gms
    kernel and the XLA forward follow, so the bf16 variant is
    tolerance-gated, never a silent precision downgrade."""
    f32 = jnp.float32
    cdt = None if compute_dtype is None else jnp.dtype(compute_dtype)

    def mm(a, b):
        # matmul-site cast: bf16 (or other compute dtype) operands, f32
        # accumulation — identical to `a @ b` when cdt is None
        if cdt is not None:
            a = a.astype(cdt)
            b = b.astype(cdt)
        return jnp.dot(a, b, preferred_element_type=f32)

    def kernel(*refs):
        rel_ref, ints_ref, ew_ref, eb_ref, ke_ref, hw_ref, hb_ref = refs[:7]
        layer_refs = refs[7:7 + 3 * num_layers]
        feat_ref = refs[7 + 3 * num_layers]
        # refs[8+3L : 14+3L] are the aliased mirror seed inputs — never
        # read (the aliased OUTPUT refs below arrive with the same bytes)
        out0 = 7 + 3 * num_layers + 1 + 6
        (kind_o, nmask_o, esrc_o, edst_o, erel_o, emask_o,
         logits_ref, probs_ref) = refs[out0:out0 + 8]
        h_ref, agg_ref, deg_ref, gath_ref, msg_ref = refs[out0 + 8:]

        # phase 1: delta scatter (drop semantics — the padding sentinel
        # is out of range, exactly the composed tick's mode="drop")
        def scat_aux(j, _):
            idx = ints_ref[j]

            @pl.when(idx < pn)
            def _():
                kind_o[idx] = ints_ref[pk + j]
                nmask_o[idx] = ints_ref[2 * pk + j].astype(f32)
            return 0

        jax.lax.fori_loop(0, pk, scat_aux, 0)
        o = 3 * pk

        def scat_edge(j, _):
            slot = ints_ref[o + j]

            @pl.when(slot < pe)
            def _():
                esrc_o[slot] = ints_ref[o + ek + j]
                edst_o[slot] = ints_ref[o + 2 * ek + j]
                erel_o[slot] = ints_ref[o + 3 * ek + j]
                emask_o[slot] = ints_ref[o + 4 * ek + j].astype(f32)
            return 0

        jax.lax.fori_loop(0, ek, scat_edge, 0)

        # degree over the scattered mirror (sums of 0/1 — exact in any
        # order, so the per-edge fold bit-matches the XLA segment_sum)
        deg_ref[:] = jnp.zeros(deg_ref.shape, f32)

        def deg_body(i, _):
            d = edst_o[i]
            deg_ref[d] = deg_ref[d] + emask_o[i]
            return 0

        jax.lax.fori_loop(0, pe, deg_body, 0)
        degv = deg_ref[:]
        inv_deg = jnp.where(degv > 0, 1.0 / jnp.maximum(degv, 1.0), 0.0)

        # phase 2: embed, then the relation-bucketed rounds
        kind_v = kind_o[:]
        h0 = jax.nn.relu(mm(feat_ref[:], ew_ref[:]) + eb_ref[:]
                         + ke_ref[:][kind_v])
        h_ref[:] = h0 * nmask_o[:][:, None]

        for li in range(num_layers):
            ws_ref = layer_refs[3 * li]
            wr_ref = layer_refs[3 * li + 1]
            b_ref = layer_refs[3 * li + 2]
            agg_ref[:] = jnp.zeros(agg_ref.shape, f32)

            def tile_body(t, _, wr_ref=wr_ref):
                base_e = t * EDGE_TILE

                def gather_row(e, _):
                    gath_ref[e, :] = (h_ref[esrc_o[base_e + e], :]
                                      * emask_o[base_e + e])
                    return 0

                jax.lax.fori_loop(0, EDGE_TILE, gather_row, 0)
                msg_ref[:] = mm(gath_ref[:], wr_ref[rel_ref[t]])

                def accum_row(e, _):
                    d = edst_o[base_e + e]
                    agg_ref[d, :] = agg_ref[d, :] + msg_ref[e, :]
                    return 0

                jax.lax.fori_loop(0, EDGE_TILE, accum_row, 0)
                return 0

            jax.lax.fori_loop(0, num_tiles, tile_body, 0)
            hv = h_ref[:]
            aggv = agg_ref[:] * inv_deg[:, None]
            h_ref[:] = jax.nn.relu(mm(hv, ws_ref[:]) + aggv + b_ref[:]) + hv

        # phase 3: score reduction — readout, logits, masked softmax
        io = 3 * pk + 5 * ek
        inc_nodes = ints_ref[io:io + pi]
        inc_mask = ints_ref[io + pi:io + 2 * pi].astype(f32)
        logits = mm(h_ref[:][inc_nodes], hw_ref[:]) + hb_ref[:]
        logits_ref[:] = logits
        probs_ref[:] = jax.nn.softmax(logits, axis=-1) * inc_mask[:, None]

    return kernel


def _fused_forward(pk, ek, pi, offs, interpret, compute_dtype, params,
                   features, kind, nmask, esrc, edst, erel, emask, ints):
    num_layers = len(params["layers"])
    pn = features.shape[0]
    pe = int(offs[-1])
    num_tiles = pe // EDGE_TILE
    hidden = params["embed_b"].shape[0]
    classes = params["head_b"].shape[0]
    rel_ids = jnp.asarray(_tile_rel_ids(offs))
    layer_ops = []
    for layer in params["layers"]:
        layer_ops += [layer["w_self"], layer["w_rel"], layer["b"]]
    inputs = [rel_ids, ints, params["embed_w"], params["embed_b"],
              params["kind_emb"], params["head_w"], params["head_b"],
              *layer_ops, features, kind, nmask, esrc, edst, erel, emask]
    mirror_base = len(inputs) - 6
    fdt = features.dtype
    out_shape = [
        jax.ShapeDtypeStruct((pn,), kind.dtype),
        jax.ShapeDtypeStruct((pn,), nmask.dtype),
        jax.ShapeDtypeStruct((pe,), esrc.dtype),
        jax.ShapeDtypeStruct((pe,), edst.dtype),
        jax.ShapeDtypeStruct((pe,), erel.dtype),
        jax.ShapeDtypeStruct((pe,), emask.dtype),
        jax.ShapeDtypeStruct((pi, classes), fdt),
        jax.ShapeDtypeStruct((pi, classes), fdt),
    ]
    return pl.pallas_call(
        _fused_kernel_factory(num_layers, pk, ek, pi, pn, pe, num_tiles,
                              compute_dtype),
        out_shape=out_shape,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * len(inputs),
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * len(out_shape),
        scratch_shapes=[
            pltpu.VMEM((pn, hidden), jnp.float32),   # resident activations
            pltpu.VMEM((pn, hidden), jnp.float32),   # per-layer accumulator
            pltpu.VMEM((pn,), jnp.float32),          # degree
            pltpu.VMEM((EDGE_TILE, hidden), jnp.float32),  # gathered rows
            pltpu.VMEM((EDGE_TILE, hidden), jnp.float32),  # message tile
        ],
        # the six resident mirrors alias their outputs: the scatter runs
        # in place on the donated serving state, never reallocating it
        input_output_aliases={mirror_base + i: i for i in range(6)},
        interpret=interpret,
    )(*inputs)


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4, 5))
def _fused_vjp(pk, ek, pi, offs, interpret, compute_dtype, params,
               features, kind, nmask, esrc, edst, erel, emask, ints):
    return _fused_forward(pk, ek, pi, offs, interpret, compute_dtype,
                          params, features, kind, nmask, esrc, edst,
                          erel, emask, ints)


def _fused_vjp_fwd(pk, ek, pi, offs, interpret, compute_dtype, params,
                   features, kind, nmask, esrc, edst, erel, emask, ints):
    out = _fused_forward(pk, ek, pi, offs, interpret, compute_dtype,
                         params, features, kind, nmask, esrc, edst, erel,
                         emask, ints)
    return out, (params, features, kind, nmask, esrc, edst, erel, emask,
                 ints)


def _fused_vjp_bwd(pk, ek, pi, offs, interpret, compute_dtype, res, cts):
    """Backward of the fused tick: rematerialize the composed
    scatter→forward→score path over the DIFFERENTIABLE Pallas gms (its
    own custom_vjp above supplies the transposed-layout backward
    kernels) and pull the output cotangents through it. Recompute-in-
    backward is the standard trade: serving pays one fused kernel,
    training — the rare direction — pays a recompute but stays entirely
    off the XLA oracle. Gradients flow to params/features/nmask/emask;
    the int mirrors and the packed delta are layout, not data."""
    params, features, kind, nmask, esrc, edst, erel, emask, ints = res
    from ..rca import gnn

    def composed(p, feats, nm, em):
        f_idx = ints[:pk]
        kind_v = ints[pk:2 * pk]
        nmask_v = ints[2 * pk:3 * pk].astype(jnp.float32)
        o = 3 * pk
        e_idx = ints[o:o + ek]
        e_src = ints[o + ek:o + 2 * ek]
        e_dst = ints[o + 2 * ek:o + 3 * ek]
        e_rel = ints[o + 3 * ek:o + 4 * ek]
        e_mask = ints[o + 4 * ek:o + 5 * ek].astype(jnp.float32)
        o += 5 * ek
        inc_nodes = ints[o:o + pi]
        inc_mask = ints[o + pi:o + 2 * pi].astype(jnp.float32)
        kind2 = kind.at[f_idx].set(kind_v, mode="drop")
        nm2 = nm.at[f_idx].set(nmask_v, mode="drop")
        esrc2 = esrc.at[e_idx].set(e_src, mode="drop")
        edst2 = edst.at[e_idx].set(e_dst, mode="drop")
        erel2 = erel.at[e_idx].set(e_rel, mode="drop")
        em2 = em.at[e_idx].set(e_mask, mode="drop")
        logits = gnn.forward(p, feats, kind2, nm2, esrc2, edst2, erel2,
                             em2, inc_nodes, rel_offsets=offs,
                             slices_sorted=False, pallas=True,
                             compute_dtype=compute_dtype)
        probs = jax.nn.softmax(logits, axis=-1) * inc_mask[:, None]
        return nm2, em2, logits, probs

    _, pullback = jax.vjp(composed, params, features, nmask, emask)
    d_params, d_feats, d_nm, d_em = pullback(
        (cts[1], cts[5], cts[6], cts[7]))
    return (d_params, d_feats, None, d_nm, None, None, None, d_em, None)


_fused_vjp.defvjp(_fused_vjp_fwd, _fused_vjp_bwd)


def pallas_fused_gnn_tick(params, features, kind, nmask, esrc, edst,
                          erel, emask, ints, *, pk: int, ek: int, pi: int,
                          rel_offsets, compute_dtype=None,
                          interpret: bool | None = None):
    """The fused streaming tick (settings.gnn_fused_tick): one
    ``pallas_call`` applying the packed aux/edge delta to the resident
    mirrors, running the full relation-bucketed forward against the
    VMEM-resident activations, and reducing logits/probs in-kernel —
    the drop-in Pallas replacement for ``rca/gnn_streaming._gnn_tick``'s
    scatter→forward→score composition (same operand layout, same
    returns; BIT-identical results at f32, tolerance-gated at
    ``compute_dtype="bfloat16"`` — bf16 matmul operands, f32
    accumulation). Requires a non-empty EDGE_TILE-aligned layout — the
    dispatcher keeps the composed tick for everything else — and a
    graph whose resident working set fits ``_VMEM_HARD_LIMIT``: past
    that the kernel cannot be placed at all, and this raises instead of
    producing a trace the compiler must reject (the DMA tick below is
    the tier for those shapes). Differentiable via ``custom_vjp``
    (backward rematerializes the composed path over the Pallas gms
    backward)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    offs = tuple(int(o) for o in rel_offsets or ())
    if len(offs) < 2 or offs[-1] <= 0 or not tiles_align(offs):
        raise ValueError(
            "pallas_fused_gnn_tick needs a non-empty EDGE_TILE-aligned "
            "relation-bucketed layout (dispatch falls back to the "
            "composed tick otherwise)")
    demand = fused_tick_vmem_bytes(
        pn=features.shape[0], pe=offs[-1], dim=features.shape[1],
        hidden=params["embed_b"].shape[0],
        classes=params["head_b"].shape[0],
        num_kinds=params["kind_emb"].shape[0],
        num_rels=params["layers"][0]["w_rel"].shape[0],
        num_layers=len(params["layers"]), pk=pk, ek=ek, pi=pi)
    if demand > _VMEM_HARD_LIMIT:
        raise ValueError(
            f"pallas_fused_gnn_tick: resident VMEM demand {demand} B "
            f"exceeds the {_VMEM_HARD_LIMIT} B placement limit — this "
            "shape is untraceable for the resident tier; use "
            "pallas_fused_gnn_tick_dma (settings.gnn_tick_dma)")
    cdt = None if compute_dtype is None else jnp.dtype(compute_dtype).name
    return _fused_vjp(int(pk), int(ek), int(pi), offs, bool(interpret),
                      cdt, params, features, kind, nmask, esrc, edst,
                      erel, emask, ints)


# -- graft-tide: beyond-VMEM DMA streaming tick + quantized tiers ----------

# Hard placement ceiling for the RESIDENT fused tick: past this the
# kernel's co-resident working set (mirrors + 2x [N, H] activations +
# tile scratch) cannot sit in VMEM on any supported part, so the entry
# point refuses the trace instead of emitting one that only fails at
# compile time. The dispatcher's SOFT threshold (settings.
# vmem_budget_bytes, default 8 MiB) flips to the DMA tier well before
# this is hit; the hard limit is the honesty backstop the 500k-pod bench
# pins (resident tier "skipped-as-untraceable").
_VMEM_HARD_LIMIT = 16 * 2 ** 20


def fused_tick_vmem_bytes(*, pn: int, pe: int, dim: int, hidden: int,
                          classes: int, num_kinds: int, num_rels: int,
                          num_layers: int, pk: int, ek: int,
                          pi: int) -> int:
    """Closed-form VMEM working set of the RESIDENT fused tick: every
    operand, output and scratch buffer of ``_fused_forward`` is
    VMEM-co-resident for the whole tick, so the demand is just the sum
    of their byte sizes. Used by the dispatcher (vs ``settings.
    vmem_budget_bytes``) to auto-select the DMA tier and by
    ``pallas_fused_gnn_tick`` (vs ``_VMEM_HARD_LIMIT``) to refuse
    untraceable shapes."""
    f = 4  # every resident buffer is f32/int32
    ints_len = 3 * pk + 5 * ek + 2 * pi
    params_b = f * (dim * hidden + hidden + num_kinds * hidden
                    + hidden * classes + classes
                    + num_layers * (hidden * hidden
                                    + num_rels * hidden * hidden
                                    + hidden))
    operands = (pn * dim * f            # feature table
                + 2 * pn * f            # kind + nmask mirrors
                + 4 * pe * f            # esrc/edst/erel/emask mirrors
                + ints_len * f + params_b)
    outputs = 2 * pi * classes * f      # logits + probs
    scratch = (2 * pn * hidden * f      # activations + accumulator
               + pn * f                 # degree
               + 2 * EDGE_TILE * hidden * f)   # gather + message tiles
    return operands + outputs + scratch


def quantize_features(features, dtype: str = "int8"):
    """Host-side node-feature quantization for the DMA tick's quantized
    table tiers. ``int8``: per-column symmetric absmax scale
    (``q = clip(round(x/scale), -127, 127)``, dequant ``q*scale`` — an
    all-zero column gets scale 0 and dequantizes EXACTLY to zero, no
    epsilon smuggled in). ``bfloat16``: plain downcast, scale is None.
    Returns ``(table, scale)``."""
    if dtype == "bfloat16":
        return features.astype(jnp.bfloat16), None
    if dtype != "int8":
        raise ValueError(f"unsupported feature quant dtype: {dtype!r}")
    scale = (jnp.max(jnp.abs(features), axis=0) / 127.0).astype(
        jnp.float32)
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(features / safe[None, :]), -127, 127)
    q = jnp.where(scale[None, :] > 0, q, 0.0).astype(jnp.int8)
    return q, scale


def dma_tick_traffic_floor(*, pn: int, pe: int, dim: int, hidden: int,
                           num_layers: int, pk: int, ek: int, pi: int,
                           feat_bytes: int = 4,
                           quant_delta_bytes: int = 0) -> int:
    """Closed-form HBM tile-traffic floor of one DMA tick — the bytes
    the streaming schedule MUST move (every block exactly once, no
    re-fetch): the delta scatter, one pass over the feature table, and
    per layer one zero + one edge sweep (windows, row gathers, RMW
    accumulates) + one blockwise update. The cost model's measured
    bytes/tick must land within 1.25x of this (bench
    ``gnn_tick_dma_vs_resident``); the slack covers call-site VMEM
    operand charges, not re-streaming."""
    f = 4
    bytes_ = 4 * ek * f                       # edge delta: 3x i32 + f32
    if quant_delta_bytes:
        bytes_ += pk * dim * quant_delta_bytes    # fq row scatter
    bytes_ += pn * dim * feat_bytes           # embed: feature read
    bytes_ += pn * hidden * f                 # embed: h0 write
    per_layer = (pn * hidden * f              # zero the accumulator
                 + 3 * pe * f                 # (src, dst, mask) windows
                 + pe * hidden * f            # row gathers
                 + 2 * pe * hidden * f        # RMW read + write
                 + 3 * pn * hidden * f)       # update: hv + agg in, h out
    bytes_ += num_layers * per_layer
    bytes_ += pi * hidden * f                 # readout row gathers
    return bytes_


def _dma_kernel_factory(num_layers: int, pk: int, ek: int, pi: int,
                        pn: int, pe: int, num_tiles: int, nb: int,
                        dim: int, hidden: int, classes: int,
                        feat_quant: str, compute_dtype):
    """Build the DMA streaming tick kernel body. Same phase structure
    and FOLD ORDER as ``_fused_kernel_factory`` — delta scatter, embed,
    ``num_layers`` edge sweeps, readout — but every O(N)/O(E) table
    (features, edge mirror, both activation buffers) lives in
    ``memory_space=ANY`` (HBM) and moves through VMEM staging:

    * the edge sweep double-buffers EDGE_TILE ``(src, dst, mask)``
      windows — the prefetch of tile ``t+1`` is issued before the wait
      on tile ``t``, so the copy overlaps compute (static slot parity:
      two tiles per loop step, slots 0/1);
    * source-row gathers and the per-destination accumulate are
      row-granular DMAs against the HBM activations, applied in exact
      edge order — the f32 path is bit-identical to the resident kernel;
    * embed and the per-layer update stream ``nb``-row node blocks
      (sequential copy in, compute, copy out);
    * the activations ping-pong between the two donated HBM buffers:
      layer ``li`` reads ``buf[li % 2]`` and accumulates+updates into
      ``buf[(li+1) % 2]`` (zeroed blockwise first), so neither is ever
      reallocated.

    Only kind/nmask/degree ([N] vectors) and the staging windows are
    VMEM-resident. ``feat_quant`` ("bfloat16"/"int8") dequantizes
    feature blocks during the embed stream; int8 uses the per-column
    scale operand. ``compute_dtype`` casts matmul operands only (f32
    accumulation), exactly like the resident kernel."""
    f32 = jnp.float32
    cdt = None if compute_dtype is None else jnp.dtype(compute_dtype)
    num_blocks = pn // nb
    quant = feat_quant in ("bfloat16", "int8")
    n_extra = {"": 0, "bfloat16": 1, "int8": 2}[feat_quant]
    mb = 7 + 3 * num_layers + 1 + n_extra     # first mirror-seed index
    n_out = 10 + (1 if quant else 0)

    def mm(a, b):
        if cdt is not None:
            a = a.astype(cdt)
            b = b.astype(cdt)
        return jnp.dot(a, b, preferred_element_type=f32)

    def kernel(*refs):
        rel_ref, ints_ref, ew_ref, eb_ref, ke_ref, hw_ref, hb_ref = refs[:7]
        layer_refs = refs[7:7 + 3 * num_layers]
        feat_in = refs[7 + 3 * num_layers]
        fq_rows_ref = refs[7 + 3 * num_layers + 1] if quant else None
        scale_ref = refs[mb - 1] if feat_quant == "int8" else None
        # refs[mb : mb+8] are aliased seeds (mirrors + h ping-pong) —
        # never read; the aliased output refs below see the same bytes
        out0 = mb + 8
        (kind_o, nmask_o, esrc_o, edst_o, erel_o, emask_o,
         logits_ref, probs_ref, ha_o, hb_o) = refs[out0:out0 + 10]
        feat_o = refs[out0 + 10] if quant else None
        (deg_ref, gath_ref, msg_ref, row_ref, ev_ref, fblk_ref,
         hblk_ref, ablk_ref, srcw_ref, dstw_ref, maskw_ref, ro_ref,
         sem_e, sem_blk, sem_row) = refs[out0 + n_out:]
        feat_src = feat_o if quant else feat_in
        bufs = (ha_o, hb_o)

        def cp(src, dst, sem=sem_blk):
            c = pltpu.make_async_copy(src, dst, sem)
            c.start()
            c.wait()

        # phase 1: delta scatter. kind/nmask are VMEM-resident (direct
        # stores, as in the resident kernel); the edge mirror is HBM, so
        # each live slot lands via a 1-element DMA from the ints slab
        # (emask stages through a f32 scalar — the slab is int32).
        def scat_aux(j, _):
            idx = ints_ref[j]

            @pl.when(idx < pn)
            def _():
                kind_o[idx] = ints_ref[pk + j]
                nmask_o[idx] = ints_ref[2 * pk + j].astype(f32)
            if quant:
                @pl.when(idx < pn)
                def _():
                    cp(fq_rows_ref.at[pl.ds(j, 1), :],
                       feat_o.at[pl.ds(idx, 1), :], sem_row)
            return 0

        jax.lax.fori_loop(0, pk, scat_aux, 0)
        o = 3 * pk

        def scat_edge(j, _):
            slot = ints_ref[o + j]

            @pl.when(slot < pe)
            def _():
                cp(ints_ref.at[pl.ds(o + ek + j, 1)],
                   esrc_o.at[pl.ds(slot, 1)], sem_row)
                cp(ints_ref.at[pl.ds(o + 2 * ek + j, 1)],
                   edst_o.at[pl.ds(slot, 1)], sem_row)
                cp(ints_ref.at[pl.ds(o + 3 * ek + j, 1)],
                   erel_o.at[pl.ds(slot, 1)], sem_row)
                ev_ref[0] = ints_ref[o + 4 * ek + j].astype(f32)
                cp(ev_ref.at[pl.ds(0, 1)],
                   emask_o.at[pl.ds(slot, 1)], sem_row)
            return 0

        jax.lax.fori_loop(0, ek, scat_edge, 0)

        # phase 2: embed — stream nb-row feature blocks through VMEM,
        # dequantize in-block, write h0 blocks to buf[0]
        def emb_block(i, _):
            b0 = i * nb
            cp(feat_src.at[pl.ds(b0, nb), :], fblk_ref.at[0])
            x = fblk_ref[0]
            if feat_quant == "int8":
                x = x.astype(f32) * scale_ref[:][None, :]
            elif x.dtype != f32:
                x = x.astype(f32)
            kv = kind_o[pl.ds(b0, nb)]
            nmv = nmask_o[pl.ds(b0, nb)]
            h0 = jax.nn.relu(mm(x, ew_ref[:]) + eb_ref[:] + ke_ref[:][kv])
            hblk_ref[0] = h0 * nmv[:, None]
            cp(hblk_ref.at[0], bufs[0].at[pl.ds(b0, nb), :])
            return 0

        jax.lax.fori_loop(0, num_blocks, emb_block, 0)
        deg_ref[:] = jnp.zeros(deg_ref.shape, f32)

        # per-layer: zero the HBM accumulator, edge sweep with
        # double-buffered tile windows, blockwise update
        for li in range(num_layers):
            ws_ref = layer_refs[3 * li]
            wr_ref = layer_refs[3 * li + 1]
            b_ref = layer_refs[3 * li + 2]
            cur = bufs[li % 2]
            nxt = bufs[(li + 1) % 2]

            ablk_ref[0] = jnp.zeros((nb, hidden), f32)

            def zero_block(i, _, nxt=nxt):
                cp(ablk_ref.at[0], nxt.at[pl.ds(i * nb, nb), :])
                return 0

            jax.lax.fori_loop(0, num_blocks, zero_block, 0)

            def tile_start(t, s):
                base = t * EDGE_TILE
                for hbm, win in ((esrc_o, srcw_ref), (edst_o, dstw_ref),
                                 (emask_o, maskw_ref)):
                    pltpu.make_async_copy(
                        hbm.at[pl.ds(base, EDGE_TILE)], win.at[s],
                        sem_e.at[s]).start()

            def tile_wait(t, s):
                base = t * EDGE_TILE
                for hbm, win in ((esrc_o, srcw_ref), (edst_o, dstw_ref),
                                 (emask_o, maskw_ref)):
                    pltpu.make_async_copy(
                        hbm.at[pl.ds(base, EDGE_TILE)], win.at[s],
                        sem_e.at[s]).wait()

            def tile_compute(t, s, li=li, cur=cur, nxt=nxt, wr_ref=wr_ref):
                rel = rel_ref[t]

                def gather(e, _):
                    srow = jnp.clip(srcw_ref[s, e], 0, pn - 1)
                    cp(cur.at[pl.ds(srow, 1), :],
                       gath_ref.at[pl.ds(e, 1), :], sem_row)
                    gath_ref[e, :] = gath_ref[e, :] * maskw_ref[s, e]
                    return 0

                jax.lax.fori_loop(0, EDGE_TILE, gather, 0)
                msg_ref[:] = mm(gath_ref[:], wr_ref[rel])
                if li == 0:
                    # degree folds into the first sweep (0/1 sums —
                    # exact in any order, same as the resident kernel)
                    def deg_body(e, _):
                        d = jnp.clip(dstw_ref[s, e], 0, pn - 1)
                        deg_ref[d] = deg_ref[d] + maskw_ref[s, e]
                        return 0

                    jax.lax.fori_loop(0, EDGE_TILE, deg_body, 0)

                def accum(e, _):
                    d = jnp.clip(dstw_ref[s, e], 0, pn - 1)
                    cp(nxt.at[pl.ds(d, 1), :],
                       row_ref.at[pl.ds(0, 1), :], sem_row)
                    row_ref[0, :] = row_ref[0, :] + msg_ref[e, :]
                    cp(row_ref.at[pl.ds(0, 1), :],
                       nxt.at[pl.ds(d, 1), :], sem_row)
                    return 0

                jax.lax.fori_loop(0, EDGE_TILE, accum, 0)

            # double-buffered sweep: two tiles per step, static slots —
            # tile t+1's windows are in flight while tile t computes
            tile_start(0, 0)

            def pair_body(p, _):
                t0 = 2 * p

                @pl.when(t0 + 1 < num_tiles)
                def _():
                    tile_start(t0 + 1, 1)
                tile_wait(t0, 0)
                tile_compute(t0, 0)

                @pl.when(t0 + 2 < num_tiles)
                def _():
                    tile_start(t0 + 2, 0)

                @pl.when(t0 + 1 < num_tiles)
                def _():
                    tile_wait(t0 + 1, 1)
                    tile_compute(t0 + 1, 1)
                return 0

            jax.lax.fori_loop(0, (num_tiles + 1) // 2, pair_body, 0)

            if li == 0:
                # degree is complete after the first sweep; invert once
                # and reuse the buffer (deg_ref holds inv_deg from here)
                degv = deg_ref[:]
                deg_ref[:] = jnp.where(
                    degv > 0, 1.0 / jnp.maximum(degv, 1.0), 0.0)

            def upd_block(i, _, cur=cur, nxt=nxt, ws_ref=ws_ref,
                          b_ref=b_ref):
                b0 = i * nb
                cp(cur.at[pl.ds(b0, nb), :], hblk_ref.at[0])
                cp(nxt.at[pl.ds(b0, nb), :], ablk_ref.at[0])
                hv = hblk_ref[0]
                aggv = ablk_ref[0] * deg_ref[pl.ds(b0, nb)][:, None]
                hn = jax.nn.relu(mm(hv, ws_ref[:]) + aggv
                                 + b_ref[:]) + hv
                hblk_ref[1] = hn
                cp(hblk_ref.at[1], nxt.at[pl.ds(b0, nb), :])
                return 0

            jax.lax.fori_loop(0, num_blocks, upd_block, 0)

        # phase 3: readout — pi row gathers from the final buffer
        h_fin = bufs[num_layers % 2]
        io = 3 * pk + 5 * ek

        def ro_row(r, _):
            idx = jnp.clip(ints_ref[io + r], 0, pn - 1)
            cp(h_fin.at[pl.ds(idx, 1), :], ro_ref.at[pl.ds(r, 1), :],
               sem_row)
            return 0

        jax.lax.fori_loop(0, pi, ro_row, 0)
        inc_mask = ints_ref[io + pi:io + 2 * pi].astype(f32)
        logits = mm(ro_ref[:], hw_ref[:]) + hb_ref[:]
        logits_ref[:] = logits
        probs_ref[:] = jax.nn.softmax(logits, axis=-1) * inc_mask[:, None]

    return kernel


def _dma_forward(pk, ek, pi, offs, nb, feat_quant, compute_dtype,
                 interpret, params, features, kind, nmask, esrc, edst,
                 erel, emask, ints, h_a, h_b, fq_rows, feat_scale):
    num_layers = len(params["layers"])
    pn = features.shape[0]
    dim = features.shape[1]
    pe = int(offs[-1])
    num_tiles = pe // EDGE_TILE
    hidden = params["embed_b"].shape[0]
    classes = params["head_b"].shape[0]
    quant = feat_quant in ("bfloat16", "int8")
    rel_ids = jnp.asarray(_tile_rel_ids(offs))
    layer_ops = []
    for layer in params["layers"]:
        layer_ops += [layer["w_self"], layer["w_rel"], layer["b"]]
    inputs = [rel_ids, ints, params["embed_w"], params["embed_b"],
              params["kind_emb"], params["head_w"], params["head_b"],
              *layer_ops, features]
    vmem, any_ = pl.BlockSpec(memory_space=pltpu.VMEM), \
        pl.BlockSpec(memory_space=pltpu.ANY)
    in_specs = [vmem] * (len(inputs) - 1) + [any_]   # features are HBM
    if quant:
        inputs.append(fq_rows)
        in_specs.append(vmem)
    if feat_quant == "int8":
        inputs.append(feat_scale)
        in_specs.append(vmem)
    mirror_base = len(inputs)
    inputs += [kind, nmask, esrc, edst, erel, emask, h_a, h_b]
    in_specs += [vmem, vmem, any_, any_, any_, any_, any_, any_]
    f32 = jnp.float32
    out_shape = [
        jax.ShapeDtypeStruct((pn,), kind.dtype),
        jax.ShapeDtypeStruct((pn,), nmask.dtype),
        jax.ShapeDtypeStruct((pe,), esrc.dtype),
        jax.ShapeDtypeStruct((pe,), edst.dtype),
        jax.ShapeDtypeStruct((pe,), erel.dtype),
        jax.ShapeDtypeStruct((pe,), emask.dtype),
        jax.ShapeDtypeStruct((pi, classes), f32),
        jax.ShapeDtypeStruct((pi, classes), f32),
        jax.ShapeDtypeStruct((pn, hidden), f32),
        jax.ShapeDtypeStruct((pn, hidden), f32),
    ]
    out_specs = [vmem, vmem, any_, any_, any_, any_, vmem, vmem,
                 any_, any_]
    aliases = {mirror_base + i: i for i in range(6)}
    aliases[mirror_base + 6] = 8
    aliases[mirror_base + 7] = 9
    if quant:
        out_shape.append(
            jax.ShapeDtypeStruct((pn, dim), features.dtype))
        out_specs.append(any_)
        aliases[7 + 3 * num_layers] = 10    # the quant table itself
    return pl.pallas_call(
        _dma_kernel_factory(num_layers, pk, ek, pi, pn, pe, num_tiles,
                            nb, dim, hidden, classes, feat_quant,
                            compute_dtype),
        out_shape=out_shape,
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((pn,), f32),                  # degree / inv_deg
            pltpu.VMEM((EDGE_TILE, hidden), f32),    # gathered rows
            pltpu.VMEM((EDGE_TILE, hidden), f32),    # message tile
            pltpu.VMEM((1, hidden), f32),            # RMW row staging
            pltpu.VMEM((1,), f32),                   # emask scatter stage
            pltpu.VMEM((2, nb, dim), features.dtype),  # feature blocks
            pltpu.VMEM((2, nb, hidden), f32),        # h block staging
            pltpu.VMEM((1, nb, hidden), f32),        # agg/zero staging
            pltpu.VMEM((2, EDGE_TILE), esrc.dtype),  # src windows
            pltpu.VMEM((2, EDGE_TILE), edst.dtype),  # dst windows
            pltpu.VMEM((2, EDGE_TILE), f32),         # mask windows
            pltpu.VMEM((pi, hidden), f32),           # readout rows
            pltpu.SemaphoreType.DMA((2,)),           # tile windows
            pltpu.SemaphoreType.DMA,                 # block copies
            pltpu.SemaphoreType.DMA,                 # row-granular DMAs
        ],
        input_output_aliases=aliases,
        interpret=interpret,
    )(*inputs)


def pallas_fused_gnn_tick_dma(params, features, kind, nmask, esrc, edst,
                              erel, emask, ints, h_a, h_b, *, pk: int,
                              ek: int, pi: int, rel_offsets,
                              node_block: int = 2048,
                              compute_dtype=None, feat_quant: str = "",
                              fq_rows=None, feat_scale=None,
                              interpret: bool | None = None):
    """The beyond-VMEM streaming tick (settings.gnn_tick_dma): the same
    delta-scatter → message-pass → verdict tick as
    ``pallas_fused_gnn_tick``, with features, edge mirror and
    activations HBM-resident and streamed through double-buffered VMEM
    windows (module docstring). ``h_a``/``h_b`` are the two donated
    ``[N, hidden]`` f32 activation buffers — pure per-tick scratch the
    caller keeps across ticks so they are never reallocated; they come
    back as the last outputs. With ``feat_quant`` ("bfloat16"/"int8"),
    ``features`` IS the quantized table (aliased output — the per-tick
    ``fq_rows`` delta rows scatter into it in-kernel; ``feat_scale`` is
    the int8 per-column scale from :func:`quantize_features`).

    Returns the resident tick's 8-tuple + ``(h_a, h_b)`` (+ the updated
    quant table when ``feat_quant``). Serving-only: not differentiable.
    The f32 path is bit-identical to the resident/composed tick."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    offs = tuple(int(o) for o in rel_offsets or ())
    if len(offs) < 2 or offs[-1] <= 0 or not tiles_align(offs):
        raise ValueError(
            "pallas_fused_gnn_tick_dma needs a non-empty "
            "EDGE_TILE-aligned relation-bucketed layout")
    if feat_quant not in ("", "bfloat16", "int8"):
        raise ValueError(f"unsupported feat_quant: {feat_quant!r}")
    pn = int(features.shape[0])
    nb = min(int(node_block), pn)
    if pn % nb != 0:
        raise ValueError(
            f"node count {pn} must be a multiple of the DMA node block "
            f"{nb} (both come off power-of-two bucket ladders)")
    if feat_quant in ("bfloat16", "int8") and fq_rows is None:
        raise ValueError("feat_quant tiers need the per-tick fq_rows")
    if feat_quant == "int8" and feat_scale is None:
        raise ValueError("int8 feat_quant needs the per-column scale")
    cdt = None if compute_dtype is None else jnp.dtype(compute_dtype).name
    return _dma_forward(int(pk), int(ek), int(pi), offs, nb, feat_quant,
                        cdt, bool(interpret), params, features, kind,
                        nmask, esrc, edst, erel, emask, ints, h_a, h_b,
                        fq_rows, feat_scale)
