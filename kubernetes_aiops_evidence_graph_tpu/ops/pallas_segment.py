"""Pallas TPU kernel for the relation-bucketed message-passing hot path.

BENCH round 5 measured the 50k-node GNN forward at 7.8% of its bandwidth
roofline (41.0 ms/forward, 49.6 GB/s achieved on a 635.8 GB/s part) while
the rules scan on the same chip hit 91%. The gap is the XLA lowering of
``ops.gather_matmul_segment``: the per-slice ``[E_r, H]`` gather rows and
the per-edge scatter-adds both stream through HBM at random-row
efficiency, and the ``[E_r, H]`` message table is materialized to HBM
between the matmul and the segment-sum.

``pallas_gather_matmul_segment`` maps the SAME math (fused gather →
per-relation ``[H, H]`` matmul → dst-segment accumulation over the static
relation-bucketed edge layout) onto one tiled, VMEM-resident pipeline:

* the node table ``h`` and the ``[N, K]`` accumulator live in VMEM for the
  whole pass (the PR 1 layout shrank the gather table 9.4x to ``[Pn, H]``
  — 16 MB at the bench config, small enough to sit next to the compute);
  the accumulator is seeded from a host-side zeros input via
  ``input_output_aliases`` so the kernel contains no init branch;
* the grid streams ``EDGE_TILE``-row tiles of ``(src, dst, mask)`` — one
  relation per tile, the per-tile relation id arrives via scalar prefetch
  from a static table derived from ``rel_offsets``;
* each tile gathers its source rows into a VMEM scratch, runs ONE
  ``[EDGE_TILE, H] × [H, K]`` matmul on the MXU (compute-dtype operands,
  f32 accumulation via ``preferred_element_type``) into a second scratch,
  and accumulates the message rows into the resident accumulator —
  per-edge ``+=`` against VMEM, never a per-edge HBM scatter-add, and no
  ``[E_r, H]`` message table ever exists outside the tile.

The per-edge accumulate applies updates in exact edge order — the same
left-fold the XLA kernel's scatter-add performs — so the kernel is
BIT-IDENTICAL to ``gather_matmul_segment`` on CPU (``interpret=True``),
which is the parity contract tier-1 pins (tests/test_ops.py,
tests/test_gnn_bucketed.py). Coalescing a sorted run into a register and
flushing once was evaluated and rejected: it reassociates the fold
whenever a dst recurs across relation slices, trading the bit-parity
oracle for a micro-optimization the VMEM accumulator already made cheap.
The sorted-by-``(rel, dst)`` layout (PR 1) still matters: it makes the
accumulator walk mostly-sequential rows, so ``slices_sorted`` is kept in
the signature for dispatch symmetry with the XLA kernel.

House style follows ``experiments/pallas_rules.py``: static tables built
host-side, ``interpret=True`` on CPU (auto-detected when not forced) so
tier-1 stays hermetic, bit-parity tests against the XLA kernel. Forward/
serving only — there is no custom_vjp here; training and gradients stay
on the XLA bucketed kernel (``settings.gnn_pallas`` gates dispatch in
``rca/gnn.py``).
"""
from __future__ import annotations

from functools import lru_cache

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Edge rows per grid step. 64 divides every REL_SLICE_BUCKETS capacity
# (powers of two >= 64, then 8192-multiples — graph/snapshot.py), so tiles
# never straddle a relation slice and the per-tile relation id is a static
# table. [64, H] keeps the MXU tile busy at H = 64 while the gather loop —
# the true bottleneck — stays row-granular either way.
EDGE_TILE = 64


@lru_cache(maxsize=64)
def _tile_rel_ids(rel_offsets: tuple[int, ...]) -> np.ndarray:
    """Static per-tile relation ids: tile ``t`` covers edge rows
    ``[t*EDGE_TILE, (t+1)*EDGE_TILE)`` and belongs to exactly one relation
    slice (capacities are EDGE_TILE-aligned — checked by the caller)."""
    rels: list[int] = []
    for r in range(len(rel_offsets) - 1):
        cap = int(rel_offsets[r + 1]) - int(rel_offsets[r])
        rels.extend([r] * (cap // EDGE_TILE))
    return np.asarray(rels, np.int32)


def tiles_align(rel_offsets) -> bool:
    """Whether every relation slice capacity is a multiple of EDGE_TILE
    (true for any layout drawn from the REL_SLICE_BUCKETS ladder). The
    dispatcher falls back to the XLA kernel otherwise."""
    return all((int(hi) - int(lo)) % EDGE_TILE == 0
               for lo, hi in zip(rel_offsets[:-1], rel_offsets[1:]))


def _gms_kernel(rel_ref, acc_init_ref, h_ref, w_ref, src_ref, dst_ref,
                mask_ref, out_ref, gath_ref, msg_ref):
    """One edge tile: gather rows into VMEM scratch, one MXU matmul into
    the message scratch, per-edge accumulate into the VMEM-resident
    [N, K] output (seeded from acc_init via input/output aliasing —
    ``acc_init_ref`` is never read here)."""
    t = pl.program_id(0)

    # gather this tile's source rows (masked: padding rows contribute
    # exact zeros, matching the XLA kernel's mask-then-matmul)
    def gather_row(e, _):
        srow = src_ref[0, e]
        gath_ref[e, :] = h_ref[srow, :] * mask_ref[0, e]
        return 0

    jax.lax.fori_loop(0, EDGE_TILE, gather_row, 0)

    rel = rel_ref[t]
    msg_ref[:] = jnp.dot(gath_ref[:], w_ref[rel],
                         preferred_element_type=out_ref.dtype)

    # per-edge accumulate against VMEM, in exact edge order — the same
    # left-fold as the XLA scatter-add, hence bit-parity
    def accum_row(e, _):
        d = dst_ref[0, e]
        out_ref[d, :] = out_ref[d, :] + msg_ref[e, :]
        return 0

    jax.lax.fori_loop(0, EDGE_TILE, accum_row, 0)


def pallas_gather_matmul_segment(
    h: jax.Array,              # [N, H] node table
    w_rel: jax.Array,          # [R, H, K] per-relation transforms
    src: jax.Array,            # [E] source index, relation-bucketed layout
    dst: jax.Array,            # [E] destination/segment index
    mask: jax.Array,           # [E] 1.0 live / 0.0 padding
    rel_offsets: tuple[int, ...],   # [R+1] STATIC slice bounds into E
    num_segments: int,
    *,
    slices_sorted: bool = False,
    compute_dtype=None,
    interpret: bool | None = None,
) -> jax.Array:
    """Drop-in Pallas replacement for :func:`ops.segment.gather_matmul_segment`
    (same signature, same semantics, bit-identical results — see the
    module docstring for the tiling scheme). ``slices_sorted`` does not
    change the math here (the VMEM accumulate is order-exact either way);
    it is accepted so dispatch sites key both kernels identically.
    ``interpret=None`` auto-selects interpret mode off-TPU so tier-1 CPU
    tests exercise the kernel hermetically."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out_dtype = h.dtype
    k = w_rel.shape[-1]
    offs = tuple(int(o) for o in rel_offsets)
    e_total = offs[-1] if offs else 0
    if e_total == 0:
        return jnp.zeros((num_segments, k), out_dtype)
    if not tiles_align(offs):
        # a layout off the EDGE_TILE-aligned ladder (hand-built tests,
        # exotic configs): the XLA kernel handles any static slicing
        from .segment import gather_matmul_segment
        return gather_matmul_segment(
            h, w_rel, src, dst, mask, offs, num_segments,
            slices_sorted=slices_sorted, compute_dtype=compute_dtype)
    if compute_dtype is not None:
        # cast ONCE before the kernel, exactly like the XLA kernel: the
        # gathered rows then move at compute-dtype width and the matmul
        # still accumulates into out_dtype via preferred_element_type
        h = h.astype(compute_dtype)
        w_rel = w_rel.astype(compute_dtype)
        mask = mask.astype(compute_dtype)
    num_tiles = e_total // EDGE_TILE
    rel_ids = jnp.asarray(_tile_rel_ids(offs))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(num_tiles,),
        in_specs=[
            # accumulator seed (aliased to the output below) + node table
            # + per-relation weights: VMEM-resident for the whole pass
            # (constant index maps, so the blocks persist across grid
            # steps instead of re-streaming from HBM)
            pl.BlockSpec((num_segments, k), lambda t, rel_ref: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(h.shape, lambda t, rel_ref: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(w_rel.shape, lambda t, rel_ref: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            # edge tiles stream through: one (1, EDGE_TILE) block per step
            pl.BlockSpec((1, EDGE_TILE), lambda t, rel_ref: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, EDGE_TILE), lambda t, rel_ref: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, EDGE_TILE), lambda t, rel_ref: (t, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((num_segments, k),
                               lambda t, rel_ref: (0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((EDGE_TILE, h.shape[1]), h.dtype),  # gathered rows
            pltpu.VMEM((EDGE_TILE, k), out_dtype),     # message tile (f32)
        ],
    )
    # the zeros seed aliases the output: the accumulator starts zeroed
    # without any in-kernel init branch, and XLA can reuse the buffer
    # in place (alias indices count the scalar-prefetch operand, so the
    # seed — second overall operand — is index 1)
    return pl.pallas_call(
        _gms_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_segments, k), out_dtype),
        input_output_aliases={1: 0},
        interpret=interpret,
    )(rel_ids, jnp.zeros((num_segments, k), out_dtype), h, w_rel,
      jnp.reshape(src, (num_tiles, EDGE_TILE)),
      jnp.reshape(dst, (num_tiles, EDGE_TILE)),
      jnp.reshape(mask, (num_tiles, EDGE_TILE)))
