"""Sparse segment primitives — the framework's core device ops.

Everything graph-shaped on TPU reduces to gather → elementwise → scatter
(segment-sum/max). XLA lowers these to efficient TPU scatters; shapes are
static (padded by the snapshot bucketing) so each variant compiles once.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def scatter_add(values: jax.Array, index: jax.Array, num_segments: int) -> jax.Array:
    """segment_sum: out[s] = Σ values[e] where index[e]==s. values may be
    [E] or [E, D]; padded entries must carry zero values."""
    out_shape = (num_segments,) + values.shape[1:]
    return jnp.zeros(out_shape, dtype=values.dtype).at[index].add(values)


def scatter_max(values: jax.Array, index: jax.Array, num_segments: int,
                fill: float = 0.0) -> jax.Array:
    out_shape = (num_segments,) + values.shape[1:]
    return jnp.full(out_shape, fill, dtype=values.dtype).at[index].max(values)


def scatter_add_2d(values: jax.Array, rows: jax.Array, cols: jax.Array,
                   num_rows: int, num_cols: int) -> jax.Array:
    """out[r, c] += v over coordinate lists (for (incident, node) pair maps)."""
    return jnp.zeros((num_rows, num_cols), dtype=values.dtype).at[rows, cols].add(values)


def gather_neighbors(x: jax.Array, index: jax.Array) -> jax.Array:
    """x[index] with index padded by any in-range value (mask separately)."""
    return x[index]


def gather_matmul_segment(
    h: jax.Array,              # [N, H] node table
    w_rel: jax.Array,          # [R, H, K] per-relation transforms
    src: jax.Array,            # [E] source index, relation-bucketed layout
    dst: jax.Array,            # [E] destination/segment index
    mask: jax.Array,           # [E] 1.0 live / 0.0 padding
    rel_offsets: tuple[int, ...],   # [R+1] STATIC slice bounds into E
    num_segments: int,
    *,
    slices_sorted: bool = False,
    compute_dtype=None,
) -> jax.Array:
    """Fused gather → per-relation matmul → dst-segment-sum over a
    relation-bucketed edge layout: edges are laid out so relation ``r``
    owns the contiguous slice ``[rel_offsets[r], rel_offsets[r+1])`` (live
    prefix + mask-zeroed padding). Each slice gathers its [E_r, H] source
    rows, applies ONE [H, K] matmul, and segment-adds into the [N, K]
    accumulator — compute and HBM traffic scale with E, never N·R (the
    dense transform-then-gather kernel materializes all R transformed
    copies of the node table: [N, R, H] written + re-read per layer).

    ``rel_offsets`` must be a static tuple (bind before jitting);
    ``slices_sorted=True`` promises dst is non-decreasing WITHIN each
    slice, letting every per-slice scatter take the sorted fast path.
    ``compute_dtype`` (e.g. jnp.bfloat16) casts the matmul operands only;
    products and the segment accumulation stay in ``h.dtype`` (f32
    accumulation), so precision loss is bounded to one rounding per
    product term.
    """
    out_dtype = h.dtype
    if compute_dtype is not None:
        # cast ONCE before the gathers: the per-edge rows then move at
        # compute-dtype width (half the gather bytes for bf16), and each
        # matmul still accumulates into out_dtype via
        # preferred_element_type
        h = h.astype(compute_dtype)
        w_rel = w_rel.astype(compute_dtype)
        mask = mask.astype(compute_dtype)
    agg = jnp.zeros((num_segments, w_rel.shape[-1]), out_dtype)
    # promise_in_bounds: the layout contract guarantees src/dst < N (slice
    # padding pins dst to the last row), so the gather/scatter skip the
    # out-of-bounds clamp logic
    for r in range(len(rel_offsets) - 1):
        lo, hi = int(rel_offsets[r]), int(rel_offsets[r + 1])
        if hi <= lo:
            continue   # relation with no edges: zero-width slice
        g = h.at[src[lo:hi]].get(mode="promise_in_bounds") \
            * mask[lo:hi, None]
        msg = jax.lax.dot(g, w_rel[r], preferred_element_type=out_dtype)
        agg = agg.at[dst[lo:hi]].add(msg, indices_are_sorted=slices_sorted,
                                     mode="promise_in_bounds")
    return agg
