"""Sparse segment primitives — the framework's core device ops.

Everything graph-shaped on TPU reduces to gather → elementwise → scatter
(segment-sum/max). XLA lowers these to efficient TPU scatters; shapes are
static (padded by the snapshot bucketing) so each variant compiles once.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def scatter_add(values: jax.Array, index: jax.Array, num_segments: int) -> jax.Array:
    """segment_sum: out[s] = Σ values[e] where index[e]==s. values may be
    [E] or [E, D]; padded entries must carry zero values."""
    out_shape = (num_segments,) + values.shape[1:]
    return jnp.zeros(out_shape, dtype=values.dtype).at[index].add(values)


def scatter_max(values: jax.Array, index: jax.Array, num_segments: int,
                fill: float = 0.0) -> jax.Array:
    out_shape = (num_segments,) + values.shape[1:]
    return jnp.full(out_shape, fill, dtype=values.dtype).at[index].max(values)


def scatter_add_2d(values: jax.Array, rows: jax.Array, cols: jax.Array,
                   num_rows: int, num_cols: int) -> jax.Array:
    """out[r, c] += v over coordinate lists (for (incident, node) pair maps)."""
    return jnp.zeros((num_rows, num_cols), dtype=values.dtype).at[rows, cols].add(values)


def gather_neighbors(x: jax.Array, index: jax.Array) -> jax.Array:
    """x[index] with index padded by any in-range value (mask separately)."""
    return x[index]
