from .pallas_segment import pallas_gather_matmul_segment
from .propagate import k_hop_reach, propagate_labels
from .segment import (
    gather_matmul_segment,
    gather_neighbors,
    scatter_add,
    scatter_add_2d,
    scatter_max,
)

__all__ = [
    "k_hop_reach", "propagate_labels",
    "scatter_add", "scatter_add_2d", "scatter_max", "gather_neighbors",
    "gather_matmul_segment", "pallas_gather_matmul_segment",
]
