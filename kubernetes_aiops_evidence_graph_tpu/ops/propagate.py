"""K-hop propagation over the sparse evidence graph.

The TPU-native answer to the reference's depth-3 Cypher traversals
(apoc.path.subgraphAll maxLevel=3, neo4j.py:169-201) and the structural
"long context" analog described in SURVEY.md §5: hop count × node count is
our sequence length. Two primitives:

* :func:`k_hop_reach` — batched frontier expansion (boolean BFS) from seed
  rows, one `lax.scan` step per hop, scatter-max per step.
* :func:`propagate_labels` — iterated normalized SpMM x ← Â·x, the batched
  anomaly label-propagation of BASELINE.json configs[2].

Both take padded COO edge lists (src, dst, mask) and run entirely under jit
with static shapes.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .segment import scatter_add, scatter_max


@partial(jax.jit, static_argnames=("num_nodes", "hops"))
def k_hop_reach(
    seed_rows: jax.Array,      # [B] node index per batch row
    seed_mask: jax.Array,      # [B] 1.0 real / 0.0 pad
    edge_src: jax.Array,       # [E]
    edge_dst: jax.Array,       # [E]
    edge_mask: jax.Array,      # [E]
    num_nodes: int,
    hops: int,
) -> jax.Array:
    """Reachability within `hops` edges: returns float [B, num_nodes]."""
    # dense one-hot seed, not a (batch, row) coordinate scatter: a 2-D
    # scatter serializes on TPU and is forbidden in the hot paths
    # (analysis/invariants.py no-2d-scatter)
    reach0 = jax.nn.one_hot(seed_rows, num_nodes,
                            dtype=jnp.float32) * seed_mask[:, None]

    def step(reach, _):
        # expand: for every edge u->v, v becomes reachable if u is
        msg = reach[:, edge_src] * edge_mask[None, :]            # [B, E]
        expanded = jax.vmap(
            lambda m: scatter_max(m, edge_dst, num_nodes)
        )(msg)
        return jnp.maximum(reach, expanded), None

    reach, _ = jax.lax.scan(step, reach0, None, length=hops)
    return reach


@partial(jax.jit, static_argnames=("num_nodes", "iterations"))
def propagate_labels(
    x: jax.Array,              # [N] or [N, D] initial scores
    edge_src: jax.Array,
    edge_dst: jax.Array,
    edge_mask: jax.Array,
    num_nodes: int,
    iterations: int = 3,
    alpha: float = 0.5,
) -> jax.Array:
    """x ← (1-α)x + α·D⁻¹Aᵀx for `iterations` rounds (label propagation)."""
    deg = scatter_add(edge_mask, edge_dst, num_nodes)
    inv_deg = jnp.where(deg > 0, 1.0 / jnp.maximum(deg, 1.0), 0.0)

    def step(cur, _):
        msg = cur[edge_src]
        if msg.ndim == 1:
            msg = msg * edge_mask
        else:
            msg = msg * edge_mask[:, None]
        agg = scatter_add(msg, edge_dst, num_nodes)
        agg = agg * (inv_deg if agg.ndim == 1 else inv_deg[:, None])
        return (1.0 - alpha) * cur + alpha * agg, None

    out, _ = jax.lax.scan(step, x, None, length=iterations)
    return out
