"""Light indirection so collectors/workflow can emit metrics without
importing the observability stack eagerly (and without it existing yet in
early builds). Wired to real counters in observability/metrics.py."""
from __future__ import annotations

from typing import Any, Callable

_collector_observer: Callable[[str, Any], None] | None = None


def set_collector_observer(fn: Callable[[str, Any], None] | None) -> None:
    global _collector_observer
    _collector_observer = fn


def observe_collector(name: str, result: Any) -> None:
    if _collector_observer is not None:
        _collector_observer(name, result)
