"""Application composition root.

Wires the whole platform together — storage, graph store, dedup, rate
limiting, workflow worker (asyncio loop on a background thread), HTTP API —
the role docker-compose's aiops-api + aiops-worker pair plays for the
reference (docker-compose.yml:205-253), in one process with no external
services. Also the fix for reference defect 1: `uvicorn src.main:app`
pointed at a module that didn't exist; here `python -m
kubernetes_aiops_evidence_graph_tpu.serve` works.
"""
from __future__ import annotations

import asyncio
import threading
from typing import Any, Optional
from uuid import UUID

from .config import Settings, get_settings
from .graph import GraphBuilder
from .ingestion.api import make_server
from .ingestion.dedup import AlertDeduplicator, RateLimiter
from .models import Incident, IncidentCreate
from .observability import ALERTS_DEDUPLICATED, INCIDENTS_CREATED, configure, get_logger
from .storage import Database, DuplicateIncidentError
from .workflow import IncidentWorker, WorkflowEngine

log = get_logger("app")


class AiopsApp:
    def __init__(
        self,
        cluster: Any,
        settings: Settings | None = None,
        db: Database | None = None,
    ) -> None:
        self.settings = settings or get_settings()
        configure(self.settings.log_level)
        self.cluster = cluster
        self.db = db or Database(self.settings.db_path)
        self.builder = GraphBuilder()
        if self.settings.graph_persist_path:
            import os
            path = self.settings.graph_persist_path
            if os.path.exists(path):
                from .graph.store import EvidenceGraphStore
                # a corrupt/incompatible persist file must not block startup
                # (stop() likewise never lets persistence failures block
                # shutdown) — move it aside and start with an empty store
                try:
                    self.builder.store = EvidenceGraphStore.load(path)
                    log.info("graph_restored", path=path,
                             nodes=self.builder.store.node_count())
                except Exception as exc:  # graft-audit: allow[broad-except] corrupt persisted graph must not block startup; moved aside below
                    bad = path + ".corrupt"
                    try:
                        os.replace(path, bad)
                    except OSError:
                        bad = "<unmovable>"
                    log.error("graph_restore_failed", path=path,
                              moved_to=bad, error=str(exc))
        self.store = self.builder.store
        self._otlp = None
        if self.settings.otlp_endpoint:
            from .observability import TRACER
            from .observability.otlp import OtlpExporter
            self._otlp = OtlpExporter(self.settings.otlp_endpoint,
                                      self.settings.otel_service_name)
            TRACER.on_end = self._otlp.enqueue
            log.info("otlp_export_enabled",
                     endpoint=self.settings.otlp_endpoint)
        self.dedup = AlertDeduplicator(self.settings)
        self.rate_limiter = RateLimiter(self.settings)
        self.worker = IncidentWorker(cluster, self.db, builder=self.builder,
                                     settings=self.settings, dedup=self.dedup)
        # graft-evolve (learn/): the online learning loop, attached to the
        # worker's resident GNN scorer once serving resolves it. Built on
        # a background thread at start() — scorer construction tensorizes
        # the store, and learning must never delay first-serve.
        self.learner = None
        self._learner_thread: threading.Thread | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._loop_thread: threading.Thread | None = None
        self._server = None
        self._server_thread: threading.Thread | None = None

    # -- lifecycle --------------------------------------------------------

    def start(self, host: str | None = None, port: int | None = None) -> int:
        """Start worker loop + HTTP server; returns the bound port."""
        self._loop = asyncio.new_event_loop()
        self._loop_thread = threading.Thread(
            target=self._loop.run_forever, daemon=True, name="kaeg-worker-loop")
        self._loop_thread.start()
        asyncio.run_coroutine_threadsafe(self.worker.start(), self._loop).result()

        self._server = make_server(
            self, host or self.settings.api_host,
            self.settings.api_port if port is None else port)
        self._server_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="kaeg-http")
        self._server_thread.start()
        bound = self._server.server_address[1]
        if self.settings.learn_enabled:
            self._learner_thread = threading.Thread(
                target=self._start_learner, name="kaeg-learn-boot",
                daemon=False)
            self._learner_thread.start()
        log.info("app_started", port=bound)
        return bound

    def _start_learner(self) -> None:
        """Resolve the resident GNN scorer (may build it — off the event
        loop and off the serving path) and start the online learning
        loop. Any backend without a swappable scorer leaves learning off,
        loudly."""
        try:
            scorer = self.worker.serving_scorer()
            if scorer is None or not hasattr(scorer, "swap_params"):
                log.warning("learn_requires_gnn_scorer",
                            rca_backend=self.settings.rca_backend)
                return
            from .learn import OnlineLearner
            self.learner = OnlineLearner(self.db, [scorer],
                                         settings=self.settings)
            self.learner.start()
            log.info("learner_started",
                     interval_s=self.settings.learn_interval_s)
        except Exception as exc:  # graft-audit: allow[broad-except] learning is strictly additive: a failed learner boot must never take serving down
            log.error("learner_start_failed", error=str(exc))

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None
        if self._learner_thread is not None:
            self._learner_thread.join(timeout=30)
            self._learner_thread = None
        if self.learner is not None:
            self.learner.stop()
        if self._loop is not None:
            try:
                asyncio.run_coroutine_threadsafe(
                    self.worker.drain(), self._loop).result(timeout=30)
            except Exception as exc:  # graft-audit: allow[broad-except] drain stuck (e.g. pending approval); force shutdown
                log.warning("drain_timeout_forcing_stop", error=str(exc))
            self.worker.stop_warm()   # idempotent; covers a stuck drain
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._loop_thread.join(timeout=5)
            self._loop = None
        try:
            if self.settings.graph_persist_path:
                written = self.store.save(self.settings.graph_persist_path)
                log.info("graph_persisted",
                         path=self.settings.graph_persist_path,
                         records=written)
        except Exception as exc:  # graft-audit: allow[broad-except] never let persistence block shutdown
            log.error("graph_persist_failed", error=str(exc))
        finally:
            if self._otlp is not None:
                from .observability import TRACER
                TRACER.on_end = None
                self._otlp.close()  # final best-effort flush
            self.db.close()

    def ready(self) -> bool:
        try:
            self.db.query("SELECT 1")
            return self._loop is not None and self._loop.is_running()
        except Exception:  # graft-audit: allow[broad-except] readiness probe: any failure reads as not-ready
            return False

    # -- ingestion path (main.py:345-425 analog) --------------------------

    def ingest(self, spec: IncidentCreate) -> Optional[str]:
        """Normalize→dedup→persist→launch workflow. Returns incident id or
        None when deduplicated."""
        if self.dedup.check_duplicate(spec.fingerprint):
            ALERTS_DEDUPLICATED.inc(reason="ttl")
            return None
        incident = Incident(**spec.model_dump())
        try:
            self.db.create_incident(incident)
        except DuplicateIncidentError:
            ALERTS_DEDUPLICATED.inc(reason="storage")  # backstop (init-db.sql:27)
            return None
        self.dedup.register_fingerprint(spec.fingerprint)  # fixes defect 4
        INCIDENTS_CREATED.inc(severity=incident.severity.value)
        if self._loop is not None:
            asyncio.run_coroutine_threadsafe(
                self.worker.submit(incident), self._loop)
        return str(incident.id)

    def ingest_batch(self, cols) -> tuple[list[tuple[str, str]], int]:
        """graft-intake: columnar batch twin of :meth:`ingest`.

        One vectorized dedup probe covers the whole batch (the hashed
        ring answers every fingerprint in a handful of array compares),
        intra-batch repeats collapse to their first occurrence, and only
        the survivors — the rows that will actually become incidents —
        pay pydantic spec construction and a DB insert. A duplicate storm
        row costs a few array lanes instead of a model_dump.

        Returns ``(created_ids, duplicates)``; malformed rows were
        already masked (and counted) by the columnar normalizer."""
        import numpy as np

        from .observability import metrics as obs_metrics

        elig = np.flatnonzero(cols.eligible)
        if elig.size == 0:
            return [], 0
        fps = cols.fingerprint[elig]
        dup = self.dedup.check_batch(fps)
        # intra-batch duplicates: the dict path registers the first
        # occurrence then TTL-hits the rest — keep-first via unique
        _, first = np.unique(fps, return_index=True)
        keep = np.zeros(len(fps), bool)
        keep[first] = True
        dup |= ~keep
        duplicates = int(dup.sum())
        if duplicates:
            obs_metrics.ALERTS_DEDUPLICATED.inc(float(duplicates),
                                                reason="ttl")
            obs_metrics.INGEST_DEDUP_HITS.inc(float(duplicates),
                                              source=cols.source.value)
        created: list[tuple[str, str]] = []   # (incident id, namespace)
        registered: list[str] = []
        for spec in cols.specs(elig[~dup]):
            incident = Incident(**spec.model_dump())
            try:
                self.db.create_incident(incident)
            except DuplicateIncidentError:
                obs_metrics.ALERTS_DEDUPLICATED.inc(reason="storage")
                duplicates += 1
                continue
            registered.append(spec.fingerprint)
            INCIDENTS_CREATED.inc(severity=incident.severity.value)
            if self._loop is not None:
                asyncio.run_coroutine_threadsafe(
                    self.worker.submit(incident), self._loop)
            created.append((str(incident.id), incident.namespace))
        if registered:
            self.dedup.register_batch(registered)
        return created, duplicates

    def workflow_status(self, incident_id: str | UUID) -> dict:
        return self.worker.engine.status(f"incident-{incident_id}")

    def learning_status(self) -> dict:
        """GET /api/v1/learning: the online-learning loop's observable
        state — buffer occupancy, last gate eval, swap generation."""
        l = self.learner
        if l is None:
            return {"enabled": bool(self.settings.learn_enabled),
                    "running": False}
        return {"enabled": True, **l.status()}


def main() -> None:  # pragma: no cover - manual entrypoint
    """Serve the platform. KAEG_CLUSTER_BACKEND selects the evidence source
    (fake = hermetic demo cluster, kubernetes = live K8s/Prometheus/Loki);
    KAEG_COORDINATOR/_NUM_PROCESSES/_PROCESS_ID join a multi-host process
    group (parallel/multihost.py) before any device use."""
    from .parallel import init_distributed
    init_distributed()
    settings = get_settings()
    if settings.cluster_backend == "kubernetes":
        from .collectors.live import LiveClusterBackend
        cluster: Any = LiveClusterBackend(settings)
    else:
        from .simulator import generate_cluster
        cluster = generate_cluster(num_pods=200, seed=0)
    app = AiopsApp(cluster, settings)
    port = app.start()
    print(f"kaeg-tpu serving on :{port} (Ctrl-C to stop)")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        app.stop()
