"""Application composition root.

Wires the whole platform together — storage, graph store, dedup, rate
limiting, workflow worker (asyncio loop on a background thread), HTTP API —
the role docker-compose's aiops-api + aiops-worker pair plays for the
reference (docker-compose.yml:205-253), in one process with no external
services. Also the fix for reference defect 1: `uvicorn src.main:app`
pointed at a module that didn't exist; here `python -m
kubernetes_aiops_evidence_graph_tpu.serve` works.
"""
from __future__ import annotations

import asyncio
import collections
import sqlite3
import threading
from dataclasses import dataclass, field
from typing import Any, Optional
from uuid import UUID

from .config import Settings, get_settings
from .graph import GraphBuilder
from .ingestion.admission import AdmissionController, CircuitBreaker
from .ingestion.api import make_server
from .ingestion.dedup import AlertDeduplicator, RateLimiter
from .models import Incident, IncidentCreate
from .observability import ALERTS_DEDUPLICATED, INCIDENTS_CREATED, configure, get_logger
from .observability import metrics as obs_metrics
from .storage import Database, DuplicateIncidentError
from .workflow import IncidentWorker, WorkflowEngine

log = get_logger("app")


@dataclass
class IngestBatchResult:
    """Exact overload accounting for one columnar webhook batch: every
    eligible row lands in exactly one of created / duplicates / shed /
    sampled / spilled (the webhook_storm bench asserts the sum)."""

    created: list[tuple[str, str]] = field(default_factory=list)
    duplicates: int = 0
    shed: int = 0                  # admission gate (token bucket dry)
    sampled: int = 0               # storm-mode sampled persistence
    spilled: int = 0               # persist breaker open -> spill journal
    retry_after_s: float = 0.0


class AiopsApp:
    def __init__(
        self,
        cluster: Any,
        settings: Settings | None = None,
        db: Database | None = None,
        surge: Any = None,
    ) -> None:
        self.settings = settings or get_settings()
        configure(self.settings.log_level)
        self.cluster = cluster
        self.db = db or Database(self.settings.db_path)
        self.builder = GraphBuilder()
        if self.settings.graph_persist_path:
            import os
            path = self.settings.graph_persist_path
            if os.path.exists(path):
                from .graph.store import EvidenceGraphStore
                # a corrupt/incompatible persist file must not block startup
                # (stop() likewise never lets persistence failures block
                # shutdown) — move it aside and start with an empty store
                try:
                    self.builder.store = EvidenceGraphStore.load(path)
                    log.info("graph_restored", path=path,
                             nodes=self.builder.store.node_count())
                except Exception as exc:  # graft-audit: allow[broad-except] corrupt persisted graph must not block startup; moved aside below
                    bad = path + ".corrupt"
                    try:
                        os.replace(path, bad)
                    except OSError:
                        bad = "<unmovable>"
                    log.error("graph_restore_failed", path=path,
                              moved_to=bad, error=str(exc))
        self.store = self.builder.store
        self._otlp = None
        if self.settings.otlp_endpoint:
            from .observability import TRACER
            from .observability.otlp import OtlpExporter
            self._otlp = OtlpExporter(self.settings.otlp_endpoint,
                                      self.settings.otel_service_name)
            TRACER.on_end = self._otlp.enqueue
            log.info("otlp_export_enabled",
                     endpoint=self.settings.otlp_endpoint)
        self.dedup = AlertDeduplicator(self.settings)
        self.rate_limiter = RateLimiter(self.settings)
        # graft-storm: per-tenant token-bucket admission with severity
        # shedding on the columnar webhook path (the legacy fixed-window
        # limiter stays as the dict-path oracle's request gate), plus a
        # circuit breaker around SQLite persist — open degrades ingest to
        # the bounded spill journal instead of timing out every webhook.
        # Chaos hooks (rca/faults.py ingest stages parse|dedup|persist|
        # admit) thread through ``fault_injector``.
        self.fault_injector: Any = None
        self.admission: AdmissionController | None = None
        if getattr(self.settings, "ingest_admission", False) and \
                getattr(self.settings, "ingest_columnar", False):
            self.admission = AdmissionController(self.settings)
        self._persist_breaker = CircuitBreaker(
            "persist",
            failure_threshold=getattr(self.settings,
                                      "breaker_failure_threshold", 5),
            cooldown_s=getattr(self.settings, "breaker_cooldown_s", 2.0))
        self._persist_spill: collections.deque = collections.deque(
            maxlen=max(int(getattr(self.settings,
                                   "persist_spill_cap", 4096)), 1))
        self._spill_lock = threading.Lock()
        self._storm_sample_counter = 0
        # graft-swell: an optional shared SurgeServer fleet — the worker
        # serves off its tenant's pack, and GET /api/v1/fleet exposes
        # placement / load / scale+migration history
        self.surge = surge
        self.worker = IncidentWorker(cluster, self.db, builder=self.builder,
                                     settings=self.settings, dedup=self.dedup,
                                     surge=surge)
        # graft-evolve (learn/): the online learning loop, attached to the
        # worker's resident GNN scorer once serving resolves it. Built on
        # a background thread at start() — scorer construction tensorizes
        # the store, and learning must never delay first-serve.
        self.learner = None
        self._learner_thread: threading.Thread | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._loop_thread: threading.Thread | None = None
        self._server = None
        self._server_thread: threading.Thread | None = None

    # -- lifecycle --------------------------------------------------------

    def start(self, host: str | None = None, port: int | None = None) -> int:
        """Start worker loop + HTTP server; returns the bound port."""
        self._loop = asyncio.new_event_loop()
        self._loop_thread = threading.Thread(
            target=self._loop.run_forever, daemon=True, name="kaeg-worker-loop")
        self._loop_thread.start()
        asyncio.run_coroutine_threadsafe(self.worker.start(), self._loop).result()
        # graft-saga startup sweep: a PREVIOUS process that died mid-
        # workflow left incidents stuck INVESTIGATING with expired leases
        # — reclaim and re-enter them through the journal-replay path
        # before taking new traffic (the periodic sweep keeps watching)
        resumed = asyncio.run_coroutine_threadsafe(
            self.worker.resume_orphans(), self._loop).result()
        if resumed:
            log.info("startup_resume_sweep", resumed=resumed)

        self._server = make_server(
            self, host or self.settings.api_host,
            self.settings.api_port if port is None else port)
        self._server_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="kaeg-http")
        self._server_thread.start()
        bound = self._server.server_address[1]
        if self.settings.learn_enabled:
            self._learner_thread = threading.Thread(
                target=self._start_learner, name="kaeg-learn-boot",
                daemon=False)
            self._learner_thread.start()
        log.info("app_started", port=bound)
        return bound

    def _start_learner(self) -> None:
        """Resolve the resident GNN scorer (may build it — off the event
        loop and off the serving path) and start the online learning
        loop. Any backend without a swappable scorer leaves learning off,
        loudly."""
        try:
            scorer = self.worker.serving_scorer()
            if scorer is None or not hasattr(scorer, "swap_params"):
                log.warning("learn_requires_gnn_scorer",
                            rca_backend=self.settings.rca_backend)
                return
            from .learn import OnlineLearner
            self.learner = OnlineLearner(self.db, [scorer],
                                         settings=self.settings)
            self.learner.start()
            log.info("learner_started",
                     interval_s=self.settings.learn_interval_s)
        except Exception as exc:  # graft-audit: allow[broad-except] learning is strictly additive: a failed learner boot must never take serving down
            log.error("learner_start_failed", error=str(exc))

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None
        if self._learner_thread is not None:
            self._learner_thread.join(timeout=30)
            self._learner_thread = None
        if self.learner is not None:
            self.learner.stop()
        if self._loop is not None:
            try:
                asyncio.run_coroutine_threadsafe(
                    self.worker.drain(), self._loop).result(timeout=30)
            except Exception as exc:  # graft-audit: allow[broad-except] drain stuck (e.g. pending approval); force shutdown
                log.warning("drain_timeout_forcing_stop", error=str(exc))
            self.worker.stop_warm()   # idempotent; covers a stuck drain
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._loop_thread.join(timeout=5)
            self._loop = None
        try:
            if self.settings.graph_persist_path:
                written = self.store.save(self.settings.graph_persist_path)
                log.info("graph_persisted",
                         path=self.settings.graph_persist_path,
                         records=written)
        except Exception as exc:  # graft-audit: allow[broad-except] never let persistence block shutdown
            log.error("graph_persist_failed", error=str(exc))
        finally:
            if self._otlp is not None:
                from .observability import TRACER
                TRACER.on_end = None
                self._otlp.close()  # final best-effort flush
            self.db.close()

    def ready(self) -> bool:
        try:
            self.db.query("SELECT 1")
            return self._loop is not None and self._loop.is_running()
        except Exception:  # graft-audit: allow[broad-except] readiness probe: any failure reads as not-ready
            return False

    # -- ingestion path (main.py:345-425 analog) --------------------------

    def ingest(self, spec: IncidentCreate) -> Optional[str]:
        """Normalize→dedup→persist→launch workflow. Returns incident id or
        None when deduplicated."""
        if self.dedup.check_duplicate(spec.fingerprint):
            ALERTS_DEDUPLICATED.inc(reason="ttl")
            return None
        incident = Incident(**spec.model_dump())
        outcome = self._persist_incident(incident)
        if outcome == "duplicate":
            ALERTS_DEDUPLICATED.inc(reason="storage")  # backstop (init-db.sql:27)
            return None
        self.dedup.register_fingerprint(spec.fingerprint)  # fixes defect 4
        if outcome == "spilled":
            # persist breaker open: the incident waits in the bounded
            # spill journal and launches its workflow on replay — the
            # webhook is acknowledged with its id, not timed out
            return str(incident.id)
        INCIDENTS_CREATED.inc(severity=incident.severity.value)
        self._submit_workflow(incident)
        return str(incident.id)

    def _submit_workflow(self, incident: Incident) -> None:
        if self._loop is not None:
            asyncio.run_coroutine_threadsafe(
                self.worker.submit(incident), self._loop)

    # -- persist breaker + spill journal (graft-storm) --------------------

    def _persist_incident(self, incident: Incident) -> str:
        """One guarded DB insert: ``created`` | ``duplicate`` |
        ``spilled``. A wedged SQLite (N consecutive failures) opens the
        persist breaker; while open every incident costs one state check
        and a bounded-deque append instead of a timeout, and the
        half-open probe's first success replays the spill."""
        inj = self.fault_injector
        br = self._persist_breaker
        if not br.allow():
            self._spill(incident)
            return "spilled"
        try:
            if inj is not None:
                inj.at("persist")
            self.db.create_incident(incident)
        except DuplicateIncidentError:
            br.record_success()
            return "duplicate"
        except (sqlite3.Error, OSError, RuntimeError) as exc:
            br.record_failure()
            log.error("persist_failed", error=str(exc),
                      breaker=br.state)
            self._spill(incident)
            return "spilled"
        br.record_success()
        if self._persist_spill:
            self._replay_spill()
        return "created"

    def _spill(self, incident: Incident) -> None:
        with self._spill_lock:
            if len(self._persist_spill) == self._persist_spill.maxlen:
                obs_metrics.PERSIST_SPILL_DROPPED.inc()
            self._persist_spill.append(incident)
        obs_metrics.PERSIST_SPILLED.inc()

    def _replay_spill(self) -> int:
        """Drain the spill journal through the (now healthy) DB in spill
        order; stops — leaving the rest spilled — on the first fresh
        failure. Replayed incidents launch their workflows late rather
        than never."""
        replayed = 0
        while True:
            with self._spill_lock:
                if not self._persist_spill:
                    return replayed
                incident = self._persist_spill.popleft()
            try:
                self.db.create_incident(incident)
            except DuplicateIncidentError:
                obs_metrics.ALERTS_DEDUPLICATED.inc(reason="storage")
                continue
            except (sqlite3.Error, OSError, RuntimeError) as exc:
                self._persist_breaker.record_failure()
                with self._spill_lock:
                    self._persist_spill.appendleft(incident)
                log.error("spill_replay_failed", error=str(exc))
                return replayed
            replayed += 1
            obs_metrics.PERSIST_SPILL_REPLAYED.inc()
            INCIDENTS_CREATED.inc(severity=incident.severity.value)
            self._submit_workflow(incident)

    def ingest_batch(self, cols) -> IngestBatchResult:
        """graft-intake/graft-storm: columnar batch twin of
        :meth:`ingest`, with the overload ladder applied in order.

        One vectorized dedup probe covers the whole batch (the hashed
        ring answers every fingerprint in a handful of array compares —
        dedup runs FIRST so duplicates never charge the admission
        budget), intra-batch repeats collapse to their first occurrence,
        the admission gate sheds lowest-severity-first when the tenant's
        token bucket runs dry (critical never sheds), storm mode samples
        persistence of presumed re-arrivals, and only the remaining
        survivors pay pydantic spec construction and a (breaker-guarded)
        DB insert. Returns an :class:`IngestBatchResult` with exact
        per-outcome accounting."""
        import numpy as np

        res = IngestBatchResult()
        inj = self.fault_injector
        if inj is not None:
            # "parse" chaos stage: the payload-decode boundary — a fault
            # here rejects the whole batch (the webhook client retries),
            # nothing was admitted or persisted
            inj.at("parse")
        elig = np.flatnonzero(cols.eligible)
        if elig.size == 0:
            return res
        fps = cols.fingerprint[elig]
        try:
            if inj is not None:
                inj.at("dedup")
            dup = self.dedup.check_batch(fps)
        except RuntimeError as exc:
            # fail open, like the scalar path: a broken dedup window must
            # not drop alerts — the storage layer's UNIQUE-fingerprint
            # backstop still suppresses duplicates, so admitted-event
            # parity holds (chaos contract, tests/test_storm.py)
            log.error("dedup_failed_open", error=str(exc))
            dup = np.zeros(len(fps), bool)
        # intra-batch duplicates: the dict path registers the first
        # occurrence then TTL-hits the rest — keep-first via unique
        _, first = np.unique(fps, return_index=True)
        keep = np.zeros(len(fps), bool)
        keep[first] = True
        dup |= ~keep
        res.duplicates = int(dup.sum())
        if res.duplicates:
            obs_metrics.ALERTS_DEDUPLICATED.inc(float(res.duplicates),
                                                reason="ttl")
            obs_metrics.INGEST_DEDUP_HITS.inc(float(res.duplicates),
                                              source=cols.source.value)
        # admission: dedup survivors charge the tenant's token bucket;
        # shed rows answer 429 + Retry-After at the handler
        admit = np.ones(len(fps), bool)
        if self.admission is not None:
            try:
                admit, res.retry_after_s = self.admission.admit_batch(
                    cols.namespace[elig], cols.severity_code[elig],
                    chargeable=~dup)
            except RuntimeError as exc:
                # "admit" chaos stage / a broken gate fails OPEN: an
                # admission outage must never drop alerts on its own
                log.error("admission_failed_open", error=str(exc))
                admit = np.ones(len(fps), bool)
            res.shed = int((~admit & ~dup).sum())
        # storm-mode sampled persistence: fresh non-critical rows are
        # overwhelmingly re-arrivals whose ring entry was evicted —
        # persist 1-in-N, register the rest back into the ring
        survivors = ~dup & admit
        sampled_fps: list[str] = []
        if (self.admission is not None and self.admission.storm.active):
            every = int(getattr(self.settings, "storm_sample_every", 0))
            if every > 1:
                sev = cols.severity_code[elig]
                ns = cols.namespace[elig]
                for i in np.flatnonzero(survivors & (sev > 0)):
                    self._storm_sample_counter += 1
                    if self._storm_sample_counter % every:
                        survivors[i] = False
                        sampled_fps.append(str(fps[i]))
                        obs_metrics.STORM_SAMPLED_ROWS.inc(
                            tenant=str(ns[i]))
                res.sampled = len(sampled_fps)
        registered: list[str] = []
        for spec in cols.specs(elig[survivors]):
            incident = Incident(**spec.model_dump())
            outcome = self._persist_incident(incident)
            if outcome == "duplicate":
                res.duplicates += 1
                continue
            registered.append(spec.fingerprint)
            if outcome == "spilled":
                res.spilled += 1
                continue
            INCIDENTS_CREATED.inc(severity=incident.severity.value)
            self._submit_workflow(incident)
            res.created.append((str(incident.id), incident.namespace))
        if registered or sampled_fps:
            # sampled rows register too: their repeats must dedup, and
            # the row they stand in for will exist once a sample lands
            self.dedup.register_batch(registered + sampled_fps)
        return res

    def workflow_status(self, incident_id: str | UUID) -> dict:
        return self.worker.engine.status(f"incident-{incident_id}")

    def learning_status(self) -> dict:
        """GET /api/v1/learning: the online-learning loop's observable
        state — buffer occupancy, last gate eval, swap generation."""
        l = self.learner
        if l is None:
            return {"enabled": bool(self.settings.learn_enabled),
                    "running": False}
        return {"enabled": True, **l.status()}


def main() -> None:  # pragma: no cover - manual entrypoint
    """Serve the platform. KAEG_CLUSTER_BACKEND selects the evidence source
    (fake = hermetic demo cluster, kubernetes = live K8s/Prometheus/Loki);
    KAEG_COORDINATOR/_NUM_PROCESSES/_PROCESS_ID join a multi-host process
    group (parallel/multihost.py) before any device use."""
    from .parallel import init_distributed
    init_distributed()
    settings = get_settings()
    if settings.cluster_backend == "kubernetes":
        from .collectors.live import LiveClusterBackend
        cluster: Any = LiveClusterBackend(settings)
    else:
        from .simulator import generate_cluster
        cluster = generate_cluster(num_pods=200, seed=0)
    app = AiopsApp(cluster, settings)
    port = app.start()
    print(f"kaeg-tpu serving on :{port} (Ctrl-C to stop)")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        app.stop()
