"""RCA engine — plugin seam between the CPU oracle and the TPU scorer
(BASELINE.json north star: ``rca_backend={cpu|tpu}``)."""
from __future__ import annotations

from .cpu_backend import CpuRcaBackend, match_rules, rank
from .ruleset import Cond, NUM_CONDS, NUM_RULES, RULE_INDEX, RULES, Rule
from .signals import Signals, condition_vector, extract_signals

_BACKENDS = {"cpu": CpuRcaBackend}


def get_backend(name: str):
    """Resolve an RCA backend by name. The TPU backend imports jax lazily so
    CPU-only callers never pay device initialization."""
    if name == "tpu":
        from .tpu_backend import TpuRcaBackend
        _BACKENDS.setdefault("tpu", TpuRcaBackend)
        return TpuRcaBackend()
    cls = _BACKENDS.get(name)
    if cls is None:
        raise KeyError(f"unknown rca backend {name!r}; available: cpu, tpu")
    return cls()


__all__ = [
    "CpuRcaBackend", "get_backend", "match_rules", "rank",
    "Cond", "NUM_CONDS", "NUM_RULES", "RULES", "RULE_INDEX", "Rule",
    "Signals", "condition_vector", "extract_signals",
]
