"""RCA engine — plugin seam between the CPU oracle and the TPU scorer
(BASELINE.json north star: ``rca_backend={cpu|tpu}``)."""
from __future__ import annotations

from .cpu_backend import CpuRcaBackend, match_rules, rank
from .ruleset import Cond, NUM_CONDS, NUM_RULES, RULE_INDEX, RULES, Rule
from .signals import Signals, condition_vector, extract_signals

_BACKEND_CLASSES = {"cpu": CpuRcaBackend}
_INSTANCES: dict[str, object] = {}


def get_backend(name: str):
    """Resolve an RCA backend by name — memoized so the TPU backend's
    device-resident snapshot cache survives across calls. The TPU class
    imports jax lazily so CPU-only callers never pay device init."""
    inst = _INSTANCES.get(name)
    if inst is not None:
        return inst
    if name == "tpu":
        from .tpu_backend import TpuRcaBackend
        _BACKEND_CLASSES.setdefault("tpu", TpuRcaBackend)
    elif name == "gnn":
        from .gnn_backend import GnnRcaBackend
        _BACKEND_CLASSES.setdefault("gnn", GnnRcaBackend)
    cls = _BACKEND_CLASSES.get(name)
    if cls is None:
        raise KeyError(f"unknown rca backend {name!r}; available: cpu, tpu, gnn")
    return _INSTANCES.setdefault(name, cls())


__all__ = [
    "CpuRcaBackend", "get_backend", "match_rules", "rank",
    "Cond", "NUM_CONDS", "NUM_RULES", "RULES", "RULE_INDEX", "Rule",
    "Signals", "condition_vector", "extract_signals",
]
