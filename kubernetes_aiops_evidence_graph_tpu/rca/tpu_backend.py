"""TPU RCA backend — all incidents scored in one jitted device pass.

This is BASELINE.json's north star: the per-incident Python fold + rule
loop of the reference (rules_engine.py:200-234, one Temporal activity per
incident) becomes a single batched computation over the tensorized evidence
graph:

1. host prep (numpy, O(E)): evidence edges (Incident→entity AFFECTS /
   CORRELATES_WITH) labeled with their incident *row* and laid out as a
   dense bucketed [Pi, W] slot table (sorted by row; W = bucketed max
   evidence per incident); a hash join of AFFECTS(incident→pod) with
   SCHEDULED_ON(pod→node) into compact (row, node) pair ids for the
   multiple-pods-same-node condition;
2. device (jit, static shapes): the evidence fold is a dense gather +
   sum over the static W axis — no scatter at all (TPU scatter-add with
   duplicate indices serializes; the dense fold measured 4× faster at the
   50k-node config) — then condition vector = thresholded counts; rule
   matching = one [C]×[R,C] contraction; confidence/rank collapse to
   constant-folded per-rule scores (see ruleset.py) so top-1 is an argmax.

Because the signal fold and checkers mirror the CPU oracle exactly, top-1
rule ids and scores are bit-identical — enforced by the parity tests.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from uuid import UUID, uuid4

import numpy as np

import jax
import jax.numpy as jnp

from ..graph.schema import F, RelationKind
from ..graph.snapshot import GraphSnapshot
from ..models import Hypothesis, HypothesisSource, RCAResult
from ..utils.padding import bucket_for
from .ruleset import (
    Cond,
    NETWORK_ERRORS_THRESHOLD,
    MULTIPLE_PODS_THRESHOLD,
    NUM_CONDS,
    NUM_RULES,
    RULES,
    UNKNOWN_CONFIDENCE,
    UNKNOWN_FINAL_SCORE,
)

_EDGE_BUCKETS = (256, 1024, 4096, 16384, 65536, 262144)
# width buckets for the dense per-incident evidence slot table
_WIDTH_BUCKETS = (16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192, 16384)
# chunk size for the W-axis fold: bounds the materialized [Pi, chunk, DIM]
# intermediate so one evidence-heavy incident can't blow up HBM
_FOLD_CHUNK = 256

# Static rule tensors (host constants, baked into the jit closure).
_RULE_COND = np.zeros((NUM_RULES, NUM_CONDS), dtype=np.float32)
for _i, _r in enumerate(RULES):
    for _c in _r.conditions:
        _RULE_COND[_i, int(_c)] = 1.0
_RULE_REQ = _RULE_COND.sum(axis=1)
_FINAL_SCORES = np.asarray([r.final_score for r in RULES], dtype=np.float32)
_CONFIDENCES = np.asarray([r.confidence for r in RULES], dtype=np.float32)


@dataclass(frozen=True)
class DeviceBatch:
    """Host-prepared, padded arrays for one scoring pass."""
    num_incidents: int
    padded_incidents: int
    # dense evidence slots: for incident row i, ev_idx[i, :ev_cnt[i]] are
    # the node indices of its evidence entities (live slots are always a
    # contiguous prefix, so the [Pi, W] mask is derived on device from
    # ev_cnt — shipping the count vector instead of a full mask)
    ev_idx: np.ndarray         # [Pi, W] int32
    ev_cnt: np.ndarray         # [Pi] int32
    # (incident, node) pair compaction for multiple_pods_same_node
    pair_ids: np.ndarray       # [Pc] int32 — compact pair index
    pair_pod: np.ndarray       # [Pc] int32 — pod node index
    pair_mask: np.ndarray      # [Pc] f32
    pair_rows: np.ndarray      # [Pp] int32 — incident row per compact pair
    pair_rows_mask: np.ndarray # [Pp] f32
    features: np.ndarray       # [Pn, DIM] f32


def evidence_coo(snapshot: GraphSnapshot) -> tuple[np.ndarray, np.ndarray]:
    """Live evidence edges as (incident row, entity node) COO arrays.

    AFFECTS / CORRELATES_WITH edges whose src is an incident (undirected
    duplicates whose *dst* is the incident are dropped here). Invariant
    under pod reschedules — the streaming path caches this."""
    inc_row = np.full(snapshot.padded_nodes, -1, dtype=np.int64)
    real = snapshot.incident_mask > 0
    inc_row[snapshot.incident_nodes[real]] = np.arange(int(real.sum()))

    live = snapshot.edge_mask > 0
    src = snapshot.edge_src[live]
    dst = snapshot.edge_dst[live]
    rel = snapshot.edge_rel[live]
    is_ev = ((rel == int(RelationKind.AFFECTS)) | (rel == int(RelationKind.CORRELATES_WITH)))
    is_ev &= inc_row[src] >= 0
    return inc_row[src[is_ev]], dst[is_ev].astype(np.int64)


def dense_evidence_table(ev_rows: np.ndarray, ev_dst: np.ndarray,
                         pi: int) -> tuple[np.ndarray, np.ndarray]:
    """[Pi, W] slot table + per-row counts from the COO: sort edges by
    incident row, place each at its within-row slot (order-stable)."""
    order = np.argsort(ev_rows, kind="stable")
    rows_s, dst_s = ev_rows[order], ev_dst[order]
    cnt = np.bincount(rows_s, minlength=pi) if len(rows_s) else np.zeros(pi, np.int64)
    width = bucket_for(max(int(cnt.max()) if len(rows_s) else 1, 1), _WIDTH_BUCKETS)
    ev_idx = np.zeros((pi, width), np.int32)
    if len(rows_s):
        starts = np.concatenate([[0], np.cumsum(cnt)])
        slots = np.arange(len(rows_s)) - starts[rows_s]
        ev_idx[rows_s, slots] = dst_s
    return ev_idx, cnt.astype(np.int32)


def pair_tables(snapshot: GraphSnapshot, ev_rows: np.ndarray,
                ev_dst: np.ndarray) -> tuple:
    """(incident, node) pair compaction for multiple_pods_same_node.

    Joins incident->pod evidence with pod->node SCHEDULED_ON edges; the
    only part of the batch that changes on a pod reschedule, so the
    streaming path refreshes just these five small arrays."""
    pi = snapshot.padded_incidents
    live = snapshot.edge_mask > 0
    src = snapshot.edge_src[live]
    dst = snapshot.edge_dst[live]
    rel = snapshot.edge_rel[live]

    # original direction = pod side is src; reversed duplicates have a Node
    # as src — fully vectorized numpy join via a node_of_pod lookup table
    from ..graph.schema import EntityKind
    is_sched = rel == int(RelationKind.SCHEDULED_ON)
    pod_side = is_sched & (snapshot.node_kind[src] == int(EntityKind.POD))
    node_of_pod = np.full(snapshot.padded_nodes, -1, dtype=np.int64)
    node_of_pod[src[pod_side]] = dst[pod_side]

    on_node = node_of_pod[ev_dst] >= 0
    pr_rows = ev_rows[on_node]
    pr_pods = ev_dst[on_node]
    pr_nodes = node_of_pod[ev_dst[on_node]]

    if len(pr_rows):
        pair_key = pr_rows.astype(np.int64) << 32 | pr_nodes
        uniq, pair_ids = np.unique(pair_key, return_inverse=True)
        pair_rows_real = (uniq >> 32).astype(np.int32)
    else:
        pair_ids = np.zeros(0, dtype=np.int64)
        pair_rows_real = np.zeros(0, dtype=np.int32)

    pc = bucket_for(max(len(pr_rows), 1), _EDGE_BUCKETS)
    pp = bucket_for(max(len(pair_rows_real), 1), _EDGE_BUCKETS)

    def _pad(arr, size, fill=0):
        out = np.full(size, fill, dtype=np.int32)
        out[:len(arr)] = arr
        return out

    pair_mask = np.zeros(pc, np.float32); pair_mask[:len(pr_rows)] = 1.0
    pair_rows_mask = np.zeros(pp, np.float32); pair_rows_mask[:len(pair_rows_real)] = 1.0
    return (_pad(pair_ids, pc, fill=pp - 1), _pad(pr_pods, pc), pair_mask,
            _pad(pair_rows_real, pp, fill=pi - 1), pair_rows_mask)


def prepare_batch(snapshot: GraphSnapshot) -> DeviceBatch:
    """Host-side O(E) prep from a snapshot (pure numpy)."""
    pi = snapshot.padded_incidents
    ev_rows, ev_dst = evidence_coo(snapshot)
    ev_idx, ev_cnt = dense_evidence_table(ev_rows, ev_dst, pi)
    pair_ids, pair_pod, pair_mask, pair_rows, pair_rows_mask = pair_tables(
        snapshot, ev_rows, ev_dst)
    return DeviceBatch(
        num_incidents=snapshot.num_incidents,
        padded_incidents=pi,
        ev_idx=ev_idx,
        ev_cnt=ev_cnt,
        pair_ids=pair_ids,
        pair_pod=pair_pod,
        pair_mask=pair_mask,
        pair_rows=pair_rows,
        pair_rows_mask=pair_rows_mask,
        features=snapshot.features,
    )


def _aggregate(features, ev_idx, ev_cnt, pair_ids, pair_pod,
               pair_mask, pair_rows, pair_rows_mask,
               padded_incidents: int, num_pairs: int):
    """Evidence fold shared by the XLA and Pallas scoring paths."""
    # fold evidence features per incident: dense gather + masked sum over
    # the static slot axis (no scatter — TPU scatter-add with duplicate
    # indices serializes and measured ~4× slower at the 50k-node config).
    # Live slots are a contiguous prefix, so the mask is derived on device
    # from the count vector; wide tables fold in _FOLD_CHUNK slices so the
    # [Pi, chunk, DIM] intermediate stays bounded under per-incident skew.
    width = ev_idx.shape[1]

    def _fold(idx, base):
        m = (base + jax.lax.broadcasted_iota(jnp.int32, idx.shape, 1)
             < ev_cnt[:, None]).astype(features.dtype)
        return (features[idx] * m[:, :, None]).sum(axis=1)           # [Pi, DIM]

    if width <= _FOLD_CHUNK:
        counts = _fold(ev_idx, 0)
    else:
        def body(acc, i):
            sl = jax.lax.dynamic_slice_in_dim(ev_idx, i * _FOLD_CHUNK,
                                              _FOLD_CHUNK, axis=1)
            return acc + _fold(sl, i * _FOLD_CHUNK), None
        counts, _ = jax.lax.scan(
            body, jnp.zeros((padded_incidents, features.shape[1]), jnp.float32),
            jnp.arange(width // _FOLD_CHUNK))
    # multiple-pods-same-node: per (incident,node) problem-pod count,
    # then per-incident max
    problem = features[:, F.POD_PROBLEM][pair_pod] * pair_mask       # [Pc]
    per_pair = jnp.zeros((num_pairs,), jnp.float32).at[pair_ids].add(problem)
    per_row_max = jnp.zeros((padded_incidents,), jnp.float32
                            ).at[pair_rows].max(per_pair * pair_rows_mask)
    return counts, per_row_max


@partial(jax.jit, static_argnames=("padded_incidents", "num_pairs", "interpret"))
def _score_device_pallas(
    features, ev_idx, ev_cnt, pair_ids, pair_pod, pair_mask,
    pair_rows, pair_rows_mask, chain, padded_incidents: int, num_pairs: int,
    interpret: bool = False,
):
    """Aggregation + the fused Pallas rules kernel (ops/pallas_rules.py)."""
    from ..ops.pallas_rules import fused_rules_engine
    counts, per_row_max = _aggregate(
        features, ev_idx, ev_cnt, pair_ids, pair_pod, pair_mask,
        pair_rows, pair_rows_mask, padded_incidents, num_pairs)
    counts = counts + jnp.minimum(chain, 0.0)[:, None]  # see dispatch()
    return fused_rules_engine(counts, per_row_max, interpret=interpret)


@partial(jax.jit, static_argnames=("padded_incidents", "num_pairs"))
def _score_device(
    features: jax.Array,       # [Pn, DIM]
    ev_idx: jax.Array,         # [Pi, W]
    ev_cnt: jax.Array,         # [Pi]
    pair_ids: jax.Array,       # [Pc]
    pair_pod: jax.Array,       # [Pc]
    pair_mask: jax.Array,      # [Pc]
    pair_rows: jax.Array,      # [Pp]
    pair_rows_mask: jax.Array, # [Pp]
    chain: jax.Array,          # [Pi] — see dispatch()
    padded_incidents: int,
    num_pairs: int,
):
    counts, per_row_max = _aggregate(
        features, ev_idx, ev_cnt, pair_ids, pair_pod, pair_mask,
        pair_rows, pair_rows_mask, padded_incidents, num_pairs)
    counts = counts + jnp.minimum(chain, 0.0)[:, None]
    return finish_scores(counts, per_row_max, padded_incidents)


def finish_scores(counts, per_row_max, padded_incidents: int):
    """counts [Pi, DIM] + per_row_max [Pi] → full scoring outputs.

    Shared tail of the XLA path; also used by the graph-sharded pass
    (parallel/sharded_rules.py) after its ring fold."""
    # 3) condition vector [Pi, NUM_CONDS]
    c = counts
    conds = jnp.zeros((padded_incidents, NUM_CONDS), jnp.float32)
    conds = conds.at[:, Cond.WAITING_CRASHLOOP].set(c[:, F.W_CRASHLOOPBACKOFF] > 0)
    conds = conds.at[:, Cond.WAITING_IMAGE_PULL].set(
        (c[:, F.W_IMAGEPULLBACKOFF] + c[:, F.W_ERRIMAGEPULL] + c[:, F.W_IMAGEINSPECTERROR]) > 0)
    conds = conds.at[:, Cond.TERMINATED_OOM].set(c[:, F.T_OOMKILLED] > 0)
    conds = conds.at[:, Cond.TERMINATED_CONFIG].set(
        (c[:, F.T_CONTAINERCANNOTRUN] + c[:, F.T_CREATECONTAINERCONFIGERROR]) > 0)
    recent = c[:, F.HAS_RECENT_DEPLOY] > 0
    conds = conds.at[:, Cond.RECENT_DEPLOY].set(recent)
    conds = conds.at[:, Cond.NO_RECENT_DEPLOY].set(~recent)
    conds = conds.at[:, Cond.MEMORY_USAGE_HIGH].set(c[:, F.MEMORY_USAGE_HIGH] > 0)
    conds = conds.at[:, Cond.HPA_AT_MAX].set(c[:, F.HPA_AT_MAX] > 0)
    conds = conds.at[:, Cond.LATENCY_HIGH].set(c[:, F.LATENCY_HIGH] > 0)
    conds = conds.at[:, Cond.LOG_PATTERN_NETWORK].set(
        (c[:, F.LOG_NETWORK] + c[:, F.LOG_CONNECTION] + c[:, F.LOG_TIMEOUT]) > 0)
    conds = conds.at[:, Cond.NODE_UNHEALTHY].set(c[:, F.NODE_NOT_READY] > 0)
    conds = conds.at[:, Cond.MULTIPLE_PODS_SAME_NODE].set(
        per_row_max >= MULTIPLE_PODS_THRESHOLD)
    conds = conds.at[:, Cond.POD_NOT_READY].set(c[:, F.POD_NOT_READY] > 0)
    conds = conds.at[:, Cond.READINESS_PROBE_FAILING].set(c[:, F.READINESS_PROBE_FAILING] > 0)
    conds = conds.at[:, Cond.NETWORK_ERRORS_HIGH].set(
        c[:, F.NETWORK_ERROR_COUNT] >= NETWORK_ERRORS_THRESHOLD)

    # 4) rule matching: satisfied-required-count == required-count
    rule_cond = jnp.asarray(_RULE_COND)                              # [R, C]
    rule_req = jnp.asarray(_RULE_REQ)                                # [R]
    sat = conds @ rule_cond.T                                        # [Pi, R]
    matched = sat >= rule_req[None, :]

    # 5) constant-folded scoring + argmax (ties → rule-table order,
    #    matching the CPU oracle's stable sort)
    scores = jnp.where(matched, jnp.asarray(_FINAL_SCORES)[None, :], 0.0)
    any_match = matched.any(axis=1)
    top_idx = jnp.argmax(scores, axis=1)
    top_score = jnp.where(any_match, scores.max(axis=1), UNKNOWN_FINAL_SCORE)
    top_conf = jnp.where(any_match, jnp.asarray(_CONFIDENCES)[top_idx], UNKNOWN_CONFIDENCE)
    return conds, matched, scores, top_idx, any_match, top_conf, top_score


class TpuRcaBackend:
    """rca_backend="tpu" — batched scoring over a GraphSnapshot.

    Device arrays are cached per snapshot version: re-scoring the same
    snapshot (the steady-state of the streaming path) re-uses resident HBM
    buffers and skips host prep entirely.
    """

    name = "tpu"

    def __init__(self, use_pallas: bool | None = None) -> None:
        if use_pallas is None:
            from ..config import get_settings
            use_pallas = get_settings().use_pallas
        self.use_pallas = use_pallas
        self._cached_snapshot: GraphSnapshot | None = None  # strong ref: keeps
        # id()s from being reused while the cache lives
        self._device_args: tuple | None = None
        self._batch: DeviceBatch | None = None

    def _load(self, snapshot: GraphSnapshot) -> tuple[DeviceBatch, tuple, float]:
        if self._cached_snapshot is snapshot and self._device_args is not None:
            return self._batch, self._device_args, 0.0
        t0 = time.perf_counter()
        batch = prepare_batch(snapshot)
        args = (
            jnp.asarray(batch.features),
            jnp.asarray(batch.ev_idx), jnp.asarray(batch.ev_cnt),
            jnp.asarray(batch.pair_ids), jnp.asarray(batch.pair_pod),
            jnp.asarray(batch.pair_mask),
            jnp.asarray(batch.pair_rows), jnp.asarray(batch.pair_rows_mask),
        )
        self._cached_snapshot, self._batch, self._device_args = snapshot, batch, args
        return batch, args, time.perf_counter() - t0

    def dispatch(self, snapshot: GraphSnapshot, chain: jax.Array | None = None
                 ) -> tuple:
        """Enqueue one scoring pass; returns *device* arrays, no host fetch.

        This is the unit the benchmark times (device results can be consumed
        by downstream device work or fetched asynchronously; on the dev
        tunnel a synchronous fetch costs a fixed ~75 ms RTT that has nothing
        to do with the TPU).

        `chain` (f32 [padded_incidents]) lets back-to-back passes carry a
        true data dependency so no runtime can elide unfetched passes: the
        caller feeds the previous pass's top_score back in, and the kernels
        add ``min(chain, 0)`` to the aggregated counts — scores are always
        >= 0, so the result is bit-identical, but the compiler cannot prove
        that and must execute every pass in order."""
        batch, args, _ = self._load(snapshot)
        if chain is None:
            chain = jnp.zeros((batch.padded_incidents,), jnp.float32)
        if self.use_pallas:
            return _score_device_pallas(
                *args, chain,
                padded_incidents=batch.padded_incidents,
                num_pairs=int(batch.pair_rows.shape[0]),
                interpret=jax.default_backend() != "tpu",
            )
        return _score_device(
            *args, chain,
            padded_incidents=batch.padded_incidents,
            num_pairs=int(batch.pair_rows.shape[0]),
        )

    def prepared(self, snapshot: GraphSnapshot) -> DeviceBatch:
        """Public access to the (cached) host-prepared batch — used by the
        sharded scoring paths so they don't re-run prep or touch internals."""
        batch, _, _ = self._load(snapshot)
        return batch

    def score_snapshot(self, snapshot: GraphSnapshot) -> dict:
        """Score every incident in the snapshot in one device pass.

        Returns a dict of host numpy arrays keyed by incident order
        (snapshot.incident_ids); use :meth:`results` for model objects.
        """
        _, _, prep_s = self._load(snapshot)  # dispatch() below hits the cache

        t1 = time.perf_counter()
        out = self.dispatch(snapshot)
        conds, matched, scores, top_idx, any_match, top_conf, top_score = (
            jax.device_get(out))  # one batched readback
        device_s = time.perf_counter() - t1

        n = snapshot.num_incidents
        return {
            "incident_ids": snapshot.incident_ids,
            "conditions": conds[:n],
            "matched": matched[:n],
            "scores": scores[:n],
            "top_rule_index": top_idx[:n],
            "any_match": any_match[:n],
            "top_confidence": top_conf[:n],
            "top_score": top_score[:n],
            "prep_seconds": prep_s,
            "device_seconds": device_s,
        }

    def results(self, snapshot: GraphSnapshot, raw: dict | None = None) -> list[RCAResult]:
        """Materialize RCAResult models (host-side, for the workflow path)."""
        raw = raw or self.score_snapshot(snapshot)
        out: list[RCAResult] = []
        for i, inc_id in enumerate(raw["incident_ids"]):
            uid = _incident_uuid(inc_id)
            hyps: list[Hypothesis] = []
            if raw["any_match"][i]:
                matched_rules = [
                    (RULES[r], float(raw["scores"][i, r])) for r in range(NUM_RULES)
                    if raw["matched"][i, r]
                ]
                matched_rules.sort(key=lambda t: t[1], reverse=True)
                for rank, (rule, score) in enumerate(matched_rules, start=1):
                    hyps.append(Hypothesis(
                        id=uuid4(), incident_id=uid, category=rule.category,
                        title=rule.name, description=rule.description,
                        confidence=rule.confidence, final_score=score, rank=rank,
                        support_count=len(rule.conditions),
                        signal_strength=rule.evidence_strength,
                        recommended_actions=rule.recommended_actions,
                        rule_id=rule.id, backend="tpu",
                        generated_by=HypothesisSource.RULES_ENGINE,
                    ))
            else:
                from .cpu_backend import _unknown_hypothesis
                from .signals import Signals
                h = _unknown_hypothesis(uid, Signals())
                h.backend = "tpu"
                hyps = [h]
            out.append(RCAResult(
                incident_id=uid, hypotheses=hyps, top_hypothesis=hyps[0],
                rules_matched=[h.rule_id for h in hyps if h.rule_id != "unknown"],
                backend="tpu",
            ))
        return out


def _incident_uuid(node_id: str) -> UUID:
    try:
        return UUID(node_id.split(":", 1)[1])
    except (ValueError, IndexError):
        return uuid4()
