"""TPU RCA backend — all incidents scored in one jitted device pass.

This is BASELINE.json's north star: the per-incident Python fold + rule
loop of the reference (rules_engine.py:200-234, one Temporal activity per
incident) becomes a single batched computation over the tensorized evidence
graph:

1. host prep (numpy, O(E)): evidence edges (Incident→entity AFFECTS /
   CORRELATES_WITH) labeled with their incident *row* and laid out as a
   dense bucketed [Pi, W] slot table (sorted by row; W = bucketed max
   evidence per incident); a join of AFFECTS(incident→pod) with
   SCHEDULED_ON(pod→node) stamps each slot with a row-local pair id for
   the multiple-pods-same-node condition (same slot layout, see
   EvidenceLayout);
2. device (jit, static shapes): the evidence fold is a dense gather +
   sum over the static W axis — no scatter at all (TPU scatter-add with
   duplicate indices serializes; the dense fold measured 4× faster at the
   50k-node config) — and the per-(row, node) problem-pod counts ride the
   same gathered rows as a chunked one-hot contraction (pair_contract);
   then condition vector = thresholded counts; rule matching = one
   [C]×[R,C] contraction; confidence/rank collapse to constant-folded
   per-rule scores (see ruleset.py) so top-1 is an argmax.

Because the signal fold and checkers mirror the CPU oracle exactly, top-1
rule ids and scores are bit-identical — enforced by the parity tests.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from uuid import UUID, uuid4

import numpy as np

import jax
import jax.numpy as jnp

from ..graph.schema import F, RelationKind
from ..graph.snapshot import GraphSnapshot
from ..models import Hypothesis, HypothesisSource, RCAResult
from ..utils.padding import bucket_for
from .ruleset import (
    Cond,
    NETWORK_ERRORS_THRESHOLD,
    MULTIPLE_PODS_THRESHOLD,
    NUM_CONDS,
    NUM_RULES,
    RULES,
    UNKNOWN_CONFIDENCE,
    UNKNOWN_FINAL_SCORE,
)

# graft-lattice: rungs live in the declared ladder registry
# (analysis/ladders.py); the historical private aliases stay the
# import surface for the rest of the tree
from ..analysis.ladders import (EDGE_BUCKETS as _EDGE_BUCKETS,
                                PACK_BUCKETS as _PACK_BUCKETS_LADDER,
                                PAIR_WIDTH_BUCKETS as _PAIR_WIDTH_BUCKETS,
                                WIDTH_BUCKETS as _WIDTH_BUCKETS)
# chunk size for the W-axis fold: bounds the materialized [Pi, chunk, DIM]
# intermediate so one evidence-heavy incident can't blow up HBM
_FOLD_CHUNK = 256
# chunk size for the pair-width axis of the one-hot contraction: bounds the
# [Pi, chunk, pair_chunk] intermediate when one incident's pods span many
# nodes (large pair_width buckets would otherwise materialize GiB)
_PAIR_CHUNK = 64

# Static rule tensors (host constants, baked into the jit closure).
_RULE_COND = np.zeros((NUM_RULES, NUM_CONDS), dtype=np.float32)
for _i, _r in enumerate(RULES):
    for _c in _r.conditions:
        _RULE_COND[_i, int(_c)] = 1.0
_RULE_REQ = _RULE_COND.sum(axis=1)
_FINAL_SCORES = np.asarray([r.final_score for r in RULES], dtype=np.float32)
_CONFIDENCES = np.asarray([r.confidence for r in RULES], dtype=np.float32)


@dataclass(frozen=True)
class DeviceBatch:
    """Host-prepared, padded arrays for one scoring pass."""
    num_incidents: int
    padded_incidents: int
    # dense evidence slots: for incident row i, ev_idx[i, :ev_cnt[i]] are
    # the node indices of its evidence entities (live slots are always a
    # contiguous prefix, so the [Pi, W] mask is derived on device from
    # ev_cnt — shipping the count vector instead of a full mask)
    ev_idx: np.ndarray         # [Pi, W] int32
    ev_cnt: np.ndarray         # [Pi] int32
    # (incident, node) pairs for multiple_pods_same_node: each evidence slot
    # carries the row-local id of the node its pod is scheduled on (or
    # pair_width = "no node"). The device pass turns the ALREADY-GATHERED
    # evidence rows into per-(row, node) problem-pod counts with one
    # one-hot contraction — no extra gathers, no scatters (both measured
    # 0.2-0.7 ms of pure pointer-chasing on v5e-1 at the 50k config).
    ev_pair_slot: np.ndarray   # [Pi, W] int32, values in [0, pair_width]
    pair_width: int            # Wr (static): max distinct nodes per row, bucketed
    features: np.ndarray       # [Pn, DIM] f32


def evidence_coo(snapshot: GraphSnapshot) -> tuple[np.ndarray, np.ndarray]:
    """Live evidence edges as (incident row, entity node) COO arrays.

    AFFECTS / CORRELATES_WITH edges whose src is an incident (undirected
    duplicates whose *dst* is the incident are dropped here). Invariant
    under pod reschedules — the streaming path caches this."""
    inc_row = np.full(snapshot.padded_nodes, -1, dtype=np.int64)
    real = snapshot.incident_mask > 0
    inc_row[snapshot.incident_nodes[real]] = np.arange(int(real.sum()))

    live = snapshot.edge_mask > 0
    src = snapshot.edge_src[live]
    dst = snapshot.edge_dst[live]
    rel = snapshot.edge_rel[live]
    is_ev = ((rel == int(RelationKind.AFFECTS)) | (rel == int(RelationKind.CORRELATES_WITH)))
    is_ev &= inc_row[src] >= 0
    return inc_row[src[is_ev]], dst[is_ev].astype(np.int64)


@dataclass(frozen=True)
class EvidenceLayout:
    """The shared slot layout of the dense evidence table: the alignment
    between ev_idx and ev_pair_slot is load-bearing (slot (i, w) must mean
    the same evidence entry in both), so both tables derive from this one
    object instead of re-sorting independently."""
    order: np.ndarray    # permutation sorting the COO by incident row (stable)
    rows_s: np.ndarray   # sorted incident rows
    slots: np.ndarray    # within-row slot of each sorted entry
    cnt: np.ndarray      # per-row entry counts [Pi]
    width: int           # bucketed max entries per row


def evidence_layout(ev_rows: np.ndarray, pi: int) -> EvidenceLayout:
    order = np.argsort(ev_rows, kind="stable")
    rows_s = ev_rows[order]
    cnt = np.bincount(rows_s, minlength=pi) if len(rows_s) else np.zeros(pi, np.int64)
    width = bucket_for(max(int(cnt.max()) if len(rows_s) else 1, 1), _WIDTH_BUCKETS)
    if len(rows_s):
        starts = np.concatenate([[0], np.cumsum(cnt)])
        slots = np.arange(len(rows_s)) - starts[rows_s]
    else:
        slots = np.zeros(0, np.int64)
    return EvidenceLayout(order=order, rows_s=rows_s, slots=slots,
                          cnt=cnt, width=width)


def dense_evidence_table(ev_rows: np.ndarray, ev_dst: np.ndarray, pi: int,
                         layout: EvidenceLayout | None = None,
                         ) -> tuple[np.ndarray, np.ndarray]:
    """[Pi, W] slot table + per-row counts from the COO."""
    lo = layout or evidence_layout(ev_rows, pi)
    ev_idx = np.zeros((pi, lo.width), np.int32)
    if len(lo.rows_s):
        ev_idx[lo.rows_s, lo.slots] = ev_dst[lo.order]
    return ev_idx, lo.cnt.astype(np.int32)


def pair_tables(snapshot: GraphSnapshot, ev_rows: np.ndarray,
                ev_dst: np.ndarray,
                layout: EvidenceLayout | None = None,
                min_width: int = 0) -> tuple[np.ndarray, int]:
    """Per-evidence-slot pair ids for multiple_pods_same_node.

    Joins incident->pod evidence with pod->node SCHEDULED_ON edges and
    assigns each (row, node) pair a ROW-LOCAL id in [0, Wr). Returns
    ``(ev_pair_slot [Pi, W], Wr)`` aligned with the dense evidence table's
    slot layout (the SAME EvidenceLayout object — alignment is
    load-bearing): slot (i, w) holds the local pair id of evidence w's
    node, or Wr when that evidence is not a pod-on-a-node. The only part of
    the batch that changes on a pod reschedule, so the streaming path
    refreshes just this array (reusing its cached layout).

    ``min_width`` floors the returned width: streaming passes its current
    compiled pair_width so a shrinking bucket never produces a table whose
    "no node" sentinel (== the returned width) would land IN range of the
    wider one_hot the resident program was compiled for."""
    pi = snapshot.padded_incidents
    live = snapshot.edge_mask > 0
    src = snapshot.edge_src[live]
    dst = snapshot.edge_dst[live]
    rel = snapshot.edge_rel[live]

    # original direction = pod side is src; reversed duplicates have a Node
    # as src — fully vectorized numpy join via a node_of_pod lookup table
    from ..graph.schema import EntityKind
    is_sched = rel == int(RelationKind.SCHEDULED_ON)
    pod_side = is_sched & (snapshot.node_kind[src] == int(EntityKind.POD))
    node_of_pod = np.full(snapshot.padded_nodes, -1, dtype=np.int64)
    node_of_pod[src[pod_side]] = dst[pod_side]

    lo = layout or evidence_layout(ev_rows, pi)
    rows_s = lo.rows_s
    dst_s = ev_dst[lo.order]

    node_s = node_of_pod[dst_s] if len(dst_s) else dst_s
    on_node = node_s >= 0
    if on_node.any():
        pair_key = rows_s[on_node].astype(np.int64) << 32 | node_s[on_node]
        uniq, inv = np.unique(pair_key, return_inverse=True)
        pair_row = (uniq >> 32).astype(np.int64)
        per_row = np.bincount(pair_row, minlength=pi)
        wr = bucket_for(max(int(per_row.max()), 1), _PAIR_WIDTH_BUCKETS)
        starts_r = np.concatenate([[0], np.cumsum(per_row)])
        local_of_pair = np.arange(len(uniq)) - starts_r[pair_row]
    else:
        local_of_pair = np.zeros(0, np.int64)
        inv = np.zeros(0, np.int64)
        wr = _PAIR_WIDTH_BUCKETS[0]
    wr = max(wr, min_width)

    ev_pair_slot = np.full((pi, lo.width), wr, dtype=np.int32)  # wr = "no node"
    if len(rows_s) and on_node.any():
        ev_pair_slot[rows_s[on_node], lo.slots[on_node]] = local_of_pair[inv]
    return ev_pair_slot, wr


def prepare_batch(snapshot: GraphSnapshot) -> DeviceBatch:
    """Host-side O(E) prep from a snapshot (pure numpy)."""
    pi = snapshot.padded_incidents
    ev_rows, ev_dst = evidence_coo(snapshot)
    layout = evidence_layout(ev_rows, pi)   # ONE layout for both tables:
    # the ev_idx/ev_pair_slot slot alignment is load-bearing
    ev_idx, ev_cnt = dense_evidence_table(ev_rows, ev_dst, pi, layout=layout)
    ev_pair_slot, pair_width = pair_tables(snapshot, ev_rows, ev_dst,
                                           layout=layout)
    return DeviceBatch(
        num_incidents=snapshot.num_incidents,
        padded_incidents=pi,
        ev_idx=ev_idx,
        ev_cnt=ev_cnt,
        ev_pair_slot=ev_pair_slot,
        pair_width=pair_width,
        features=snapshot.features,
    )


def pair_contract(problem: jax.Array, pslot: jax.Array,
                  pair_width: int) -> jax.Array:
    """[Pi, C] problem flags × per-slot pair ids → [Pi, pair_width] counts.

    One-hot contraction, chunked on the pair axis so the materialized
    [Pi, C, _PAIR_CHUNK] intermediate stays bounded at any pair_width.
    Out-of-range ids (the "no node" sentinel, or ids outside the current
    chunk) one-hot to zero rows and drop out."""
    if pair_width <= _PAIR_CHUNK:
        onehot = jax.nn.one_hot(pslot, pair_width, dtype=problem.dtype)
        return jnp.einsum("ic,icr->ir", problem, onehot)

    def body(_, r0):
        oh = jax.nn.one_hot(pslot - r0, _PAIR_CHUNK, dtype=problem.dtype)
        return None, jnp.einsum("ic,icr->ir", problem, oh)

    _, chunks = jax.lax.scan(
        body, None, jnp.arange(0, pair_width, _PAIR_CHUNK))
    return jnp.moveaxis(chunks, 0, 1).reshape(problem.shape[0], pair_width)


def _aggregate(features, ev_idx, ev_cnt, ev_pair_slot,
               padded_incidents: int, pair_width: int):
    """Evidence fold shared by the XLA and Pallas scoring paths."""
    # fold evidence features per incident: dense gather + masked sum over
    # the static slot axis (no scatter — TPU scatter-add with duplicate
    # indices serializes and measured ~4× slower at the 50k-node config).
    # Live slots are a contiguous prefix, so the mask is derived on device
    # from the count vector; wide tables fold in _FOLD_CHUNK slices so the
    # [Pi, chunk, DIM] intermediate stays bounded under per-incident skew.
    #
    # multiple-pods-same-node rides the SAME gathered rows: each slot's
    # row-local pair id one-hots into [chunk, Wr] and contracts with the
    # slot's POD_PROBLEM flag — per-(row, node) problem-pod counts with
    # zero extra gathers (gather/scatter pair formulations measured
    # 0.2-0.7 ms of pointer-chasing on v5e-1; this adds ~nothing).
    width = ev_idx.shape[1]

    def _fold(idx, pair_slot, base):
        m = (base + jax.lax.broadcasted_iota(jnp.int32, idx.shape, 1)
             < ev_cnt[:, None]).astype(features.dtype)
        rows = features[idx] * m[:, :, None]                         # [Pi, C, DIM]
        counts = rows.sum(axis=1)                                    # [Pi, DIM]
        pair_counts = pair_contract(rows[:, :, F.POD_PROBLEM],
                                    pair_slot, pair_width)           # [Pi, Wr]
        return counts, pair_counts

    if width <= _FOLD_CHUNK:
        counts, pair_counts = _fold(ev_idx, ev_pair_slot, 0)
    else:
        def body(acc, i):
            sl = jax.lax.dynamic_slice_in_dim(ev_idx, i * _FOLD_CHUNK,
                                              _FOLD_CHUNK, axis=1)
            ps = jax.lax.dynamic_slice_in_dim(ev_pair_slot, i * _FOLD_CHUNK,
                                              _FOLD_CHUNK, axis=1)
            c, pc = _fold(sl, ps, i * _FOLD_CHUNK)
            return (acc[0] + c, acc[1] + pc), None
        (counts, pair_counts), _ = jax.lax.scan(
            body,
            (jnp.zeros((padded_incidents, features.shape[1]), jnp.float32),
             jnp.zeros((padded_incidents, pair_width), jnp.float32)),
            jnp.arange(width // _FOLD_CHUNK))
    per_row_max = pair_counts.max(axis=1)                            # [Pi]
    return counts, per_row_max


@partial(jax.jit, static_argnames=("padded_incidents", "pair_width"))
def _score_device(
    features: jax.Array,       # [Pn, DIM]
    ev_idx: jax.Array,         # [Pi, W]
    ev_cnt: jax.Array,         # [Pi]
    ev_pair_slot: jax.Array,   # [Pi, W]
    chain: jax.Array,          # [Pi] — see dispatch()
    padded_incidents: int,
    pair_width: int,
):
    counts, per_row_max = _aggregate(
        features, ev_idx, ev_cnt, ev_pair_slot, padded_incidents, pair_width)
    counts = counts + jnp.minimum(chain, 0.0)[:, None]
    return finish_scores(counts, per_row_max, padded_incidents)


def finish_scores(counts, per_row_max, padded_incidents: int):
    """counts [Pi, DIM] + per_row_max [Pi] → full scoring outputs.

    Shared tail of the XLA path; also used by the graph-sharded pass
    (parallel/sharded_rules.py) after its ring fold."""
    # 3) condition vector [Pi, NUM_CONDS]
    c = counts
    conds = jnp.zeros((padded_incidents, NUM_CONDS), jnp.float32)
    conds = conds.at[:, Cond.WAITING_CRASHLOOP].set(c[:, F.W_CRASHLOOPBACKOFF] > 0)
    conds = conds.at[:, Cond.WAITING_IMAGE_PULL].set(
        (c[:, F.W_IMAGEPULLBACKOFF] + c[:, F.W_ERRIMAGEPULL] + c[:, F.W_IMAGEINSPECTERROR]) > 0)
    conds = conds.at[:, Cond.TERMINATED_OOM].set(c[:, F.T_OOMKILLED] > 0)
    conds = conds.at[:, Cond.TERMINATED_CONFIG].set(
        (c[:, F.T_CONTAINERCANNOTRUN] + c[:, F.T_CREATECONTAINERCONFIGERROR]) > 0)
    recent = c[:, F.HAS_RECENT_DEPLOY] > 0
    conds = conds.at[:, Cond.RECENT_DEPLOY].set(recent)
    conds = conds.at[:, Cond.NO_RECENT_DEPLOY].set(~recent)
    conds = conds.at[:, Cond.MEMORY_USAGE_HIGH].set(c[:, F.MEMORY_USAGE_HIGH] > 0)
    conds = conds.at[:, Cond.HPA_AT_MAX].set(c[:, F.HPA_AT_MAX] > 0)
    conds = conds.at[:, Cond.LATENCY_HIGH].set(c[:, F.LATENCY_HIGH] > 0)
    conds = conds.at[:, Cond.LOG_PATTERN_NETWORK].set(
        (c[:, F.LOG_NETWORK] + c[:, F.LOG_CONNECTION] + c[:, F.LOG_TIMEOUT]) > 0)
    conds = conds.at[:, Cond.NODE_UNHEALTHY].set(c[:, F.NODE_NOT_READY] > 0)
    conds = conds.at[:, Cond.MULTIPLE_PODS_SAME_NODE].set(
        per_row_max >= MULTIPLE_PODS_THRESHOLD)
    conds = conds.at[:, Cond.POD_NOT_READY].set(c[:, F.POD_NOT_READY] > 0)
    conds = conds.at[:, Cond.READINESS_PROBE_FAILING].set(c[:, F.READINESS_PROBE_FAILING] > 0)
    conds = conds.at[:, Cond.NETWORK_ERRORS_HIGH].set(
        c[:, F.NETWORK_ERROR_COUNT] >= NETWORK_ERRORS_THRESHOLD)

    # 4) rule matching: satisfied-required-count == required-count
    rule_cond = jnp.asarray(_RULE_COND)                              # [R, C]
    rule_req = jnp.asarray(_RULE_REQ)                                # [R]
    sat = conds @ rule_cond.T                                        # [Pi, R]
    matched = sat >= rule_req[None, :]

    # 5) constant-folded scoring + argmax (ties → rule-table order,
    #    matching the CPU oracle's stable sort)
    scores = jnp.where(matched, jnp.asarray(_FINAL_SCORES)[None, :], 0.0)
    any_match = matched.any(axis=1)
    top_idx = jnp.argmax(scores, axis=1)
    top_score = jnp.where(any_match, scores.max(axis=1), UNKNOWN_FINAL_SCORE)
    top_conf = jnp.where(any_match, jnp.asarray(_CONFIDENCES)[top_idx], UNKNOWN_CONFIDENCE)
    return conds, matched, scores, top_idx, any_match, top_conf, top_score


class TpuRcaBackend:
    """rca_backend="tpu" — batched scoring over a GraphSnapshot.

    Device arrays are cached per snapshot version: re-scoring the same
    snapshot (the steady-state of the streaming path) re-uses resident HBM
    buffers and skips host prep entirely.
    """

    name = "tpu"

    def __init__(self) -> None:
        self._cached_snapshot: GraphSnapshot | None = None  # strong ref: keeps
        # id()s from being reused while the cache lives
        self._device_args: tuple | None = None
        self._batch: DeviceBatch | None = None

    def _load(self, snapshot: GraphSnapshot) -> tuple[DeviceBatch, tuple, float]:
        if self._cached_snapshot is snapshot and self._device_args is not None:
            return self._batch, self._device_args, 0.0
        t0 = time.perf_counter()
        batch = prepare_batch(snapshot)
        args = (
            jnp.asarray(batch.features),
            jnp.asarray(batch.ev_idx), jnp.asarray(batch.ev_cnt),
            jnp.asarray(batch.ev_pair_slot),
        )
        self._cached_snapshot, self._batch, self._device_args = snapshot, batch, args
        return batch, args, time.perf_counter() - t0

    def dispatch(self, snapshot: GraphSnapshot, chain: jax.Array | None = None
                 ) -> tuple:
        """Enqueue one scoring pass; returns *device* arrays, no host fetch.

        This is the unit the benchmark times (device results can be consumed
        by downstream device work or fetched asynchronously; on the dev
        tunnel a synchronous fetch costs a fixed ~75 ms RTT that has nothing
        to do with the TPU).

        `chain` (f32 [padded_incidents]) lets back-to-back passes carry a
        true data dependency so no runtime can elide unfetched passes: the
        caller feeds the previous pass's top_score back in, and the kernels
        add ``min(chain, 0)`` to the aggregated counts — scores are always
        >= 0, so the result is bit-identical, but the compiler cannot prove
        that and must execute every pass in order."""
        batch, args, _ = self._load(snapshot)
        if chain is None:
            chain = jnp.zeros((batch.padded_incidents,), jnp.float32)
        return _score_device(
            *args, chain,
            padded_incidents=batch.padded_incidents,
            pair_width=batch.pair_width,
        )

    def prepared(self, snapshot: GraphSnapshot) -> DeviceBatch:
        """Public access to the (cached) host-prepared batch — used by the
        sharded scoring paths so they don't re-run prep or touch internals."""
        batch, _, _ = self._load(snapshot)
        return batch

    def device_arrays(self, snapshot: GraphSnapshot) -> tuple:
        """The (cached) resident device arrays (features, ev_idx, ev_cnt,
        ev_pair_slot) — used by the roofline instrumentation
        (rca/device_metrics.py) to time the identical buffers the scoring
        pass runs on."""
        _, args, _ = self._load(snapshot)
        return args

    # result-field groups for the fetch modes of score_snapshot, in the
    # device-output order of _score_device. "top" is the narrowed serving
    # fetch: per-incident verdict fields only — the [Pi, C]/[Pi, R]
    # conditions/matched/scores tables dominate the readback bytes and
    # serving callers discard them, so they are never moved off-device.
    _FETCH_FIELDS = {
        "full": ("conditions", "matched", "scores", "top_rule_index",
                 "any_match", "top_confidence", "top_score"),
        "top": ("top_rule_index", "any_match", "top_confidence",
                "top_score"),
    }

    def score_snapshot(self, snapshot: GraphSnapshot,
                       fields: str = "full") -> dict:
        """Score every incident in the snapshot in one device pass.

        Returns a dict of host numpy arrays keyed by incident order
        (snapshot.incident_ids); use :meth:`results` for model objects
        (which needs the default ``fields="full"``). ``fields="top"``
        fetches only the top-k verdict fields — the per-condition /
        per-rule tables stay on device and their readback is never paid.
        Every fetch increments the ``aiops_serve_fetched_bytes_total``
        counter (path="score_snapshot") with the bytes actually moved.
        """
        _, _, prep_s = self._load(snapshot)  # dispatch() below hits the cache

        all_fields = self._FETCH_FIELDS["full"]
        keys = self._FETCH_FIELDS[fields]    # KeyError = unknown fetch mode
        t1 = time.perf_counter()
        out = self.dispatch(snapshot)
        dispatch_s = time.perf_counter() - t1
        t2 = time.perf_counter()
        fetched = jax.device_get(
            tuple(out[all_fields.index(k)] for k in keys))  # one readback
        fetch_s = time.perf_counter() - t2

        from ..observability import metrics as obs_metrics
        obs_metrics.SERVE_FETCHED_BYTES.inc(
            float(sum(a.nbytes for a in fetched)), path="score_snapshot")

        # graft-scope: when the caller carries a live trace (the workflow
        # snapshot-verdict path), the scoring pass joins it as a child
        # span with its dispatch/fetch splits — so the non-streaming
        # verdict path shows up in the same webhook→verdict trace anatomy
        # as the resident tick. Emitted retrospectively: zero span
        # objects in the timed windows above.
        from ..observability import scope as obs_scope
        obs_scope.emit_stage_span(
            "serve.score_snapshot",
            (("dispatch", dispatch_s), ("fetch", fetch_s)),
            fields=fields, incidents=snapshot.num_incidents)

        # finite guard (graft-shield): a poisoned feature row or device
        # fault must never surface as a NaN/inf verdict — count and log so
        # the snapshot path shares the serving path's honesty bar (the
        # shield quarantines; this batch path has no delta to quarantine,
        # so it surfaces the signal instead of silently serving garbage)
        from ..observability import get_logger
        for k, a in zip(keys, fetched):
            if a.dtype.kind == "f" and not np.isfinite(a).all():
                obs_metrics.SHIELD_NONFINITE_VERDICTS.inc(
                    path="score_snapshot")
                get_logger("tpu_backend").warning(
                    "nonfinite_verdict_field", field=k)
                obs_scope.FLIGHT_RECORDER.note_event(
                    "nonfinite_verdict_field", field=k,
                    path="score_snapshot")
                break

        n = snapshot.num_incidents
        res = {
            "incident_ids": snapshot.incident_ids,
            "prep_seconds": prep_s,
            "dispatch_seconds": dispatch_s,
            "fetch_seconds": fetch_s,
            "device_seconds": dispatch_s + fetch_s,
            "fetched_fields": fields,
        }
        for k, a in zip(keys, fetched):
            res[k] = a[:n]
        return res

    # static incident-bucket ladder for the packed cross-tenant pass
    # (graft-surge): the packed row count pads up this ladder so the
    # number of compiled variants stays discrete as tenant sets vary
    # (rungs declared in analysis/ladders.py — graft-lattice)
    _PACK_BUCKETS = _PACK_BUCKETS_LADDER

    def score_snapshots(self, snapshots: "list[GraphSnapshot]",
                        fields: str = "top") -> list[dict]:
        """Cross-tenant verdict batching on the SNAPSHOT path: score k
        tenants' snapshots in ONE ``_score_device`` pass (graft-surge).

        The per-tenant batches pack along the incident axis (padded rows
        concatenated, then padded up the static ``_PACK_BUCKETS`` ladder)
        with each tenant's evidence slot indices offset by its feature
        base — per-tenant node-id namespacing in the slot space. Widths
        and the pair bucket take the max over tenants; the extra padded
        slots fold exact zeros, so each verdict row is bit-identical to
        that tenant's own ``score_snapshot`` (pinned by
        tests/test_surge.py at every ladder rung). One dispatch + one
        readback total; per-tenant row slices unpack at the fetch."""
        if not snapshots:
            return []
        batches = [prepare_batch(s) for s in snapshots]
        width = max(b.ev_idx.shape[1] for b in batches)
        pair_width = max(b.pair_width for b in batches)
        total = sum(b.padded_incidents for b in batches)
        pi = bucket_for(total, self._PACK_BUCKETS)
        features = np.concatenate([b.features for b in batches], axis=0)
        ev_idx = np.zeros((pi, width), np.int32)
        ev_cnt = np.zeros(pi, np.int32)
        ev_pair = np.full((pi, width), pair_width, np.int32)
        slices: list[tuple[int, int]] = []
        row = base = 0
        for b in batches:
            k, w = b.padded_incidents, b.ev_idx.shape[1]
            # slot indices shift into the tenant's feature region; the
            # dead slots beyond ev_cnt gather garbage rows that the
            # count-derived mask multiplies to exact zero, same as the
            # single-tenant pass
            ev_idx[row:row + k, :w] = b.ev_idx + base
            ev_cnt[row:row + k] = b.ev_cnt
            # re-stamp each tenant's "no node" sentinel to the pack's
            ev_pair[row:row + k, :w] = np.where(
                b.ev_pair_slot >= b.pair_width, pair_width, b.ev_pair_slot)
            slices.append((row, k))
            row += k
            base += b.features.shape[0]
        t1 = time.perf_counter()
        out = _score_device(
            jnp.asarray(features), jnp.asarray(ev_idx),
            jnp.asarray(ev_cnt), jnp.asarray(ev_pair),
            jnp.zeros((pi,), jnp.float32),
            padded_incidents=pi, pair_width=pair_width)
        dispatch_s = time.perf_counter() - t1
        all_fields = self._FETCH_FIELDS["full"]
        keys = self._FETCH_FIELDS[fields]
        t2 = time.perf_counter()
        fetched = jax.device_get(
            tuple(out[all_fields.index(k)] for k in keys))  # one readback
        fetch_s = time.perf_counter() - t2
        from ..observability import metrics as obs_metrics
        obs_metrics.SERVE_FETCHED_BYTES.inc(
            float(sum(a.nbytes for a in fetched)), path="score_snapshots")
        obs_metrics.SERVE_BATCH_INCIDENTS.observe(
            float(sum(s.num_incidents for s in snapshots)),
            tenants=str(len(snapshots)))
        res: list[dict] = []
        for snap, (r0, _k) in zip(snapshots, slices):
            n = snap.num_incidents
            one = {
                "incident_ids": snap.incident_ids,
                "dispatch_seconds": dispatch_s,
                "fetch_seconds": fetch_s,
                "device_seconds": dispatch_s + fetch_s,
                "fetched_fields": fields,
                "device_passes": 1,
            }
            for k, a in zip(keys, fetched):
                one[k] = a[r0:r0 + n]
            res.append(one)
        return res

    def results(self, snapshot: GraphSnapshot | None = None,
                raw: dict | None = None) -> list[RCAResult]:
        """Materialize RCAResult models (host-side, for the workflow path).

        Accepts either a snapshot to score, or a pre-computed ``raw`` dict —
        e.g. a StreamingScorer.rescore() result, whose keys are identical —
        in which case no snapshot is needed at all (the serving path).

        A NARROWED raw dict (``score_snapshot(fields="top")`` — no
        ``matched``/``scores`` tables, they never left the device)
        materializes the TOP hypothesis only: per-incident top rule +
        top score at rank 1, the verdict the workflow acts on
        (runbook/remediation key off ``top_hypothesis``). The wide fetch
        stays the path for every-matched-rule hypothesis lists."""
        if raw is None:
            if snapshot is None:
                raise ValueError("results() needs a snapshot or a raw dict")
            raw = self.score_snapshot(snapshot)
        narrowed = "matched" not in raw or "scores" not in raw
        out: list[RCAResult] = []
        for i, inc_id in enumerate(raw["incident_ids"]):
            uid = _incident_uuid(inc_id)
            hyps: list[Hypothesis] = []
            if raw["any_match"][i]:
                if narrowed:
                    matched_rules = [
                        (RULES[int(raw["top_rule_index"][i])],
                         float(raw["top_score"][i]))]
                else:
                    matched_rules = [
                        (RULES[r], float(raw["scores"][i, r]))
                        for r in range(NUM_RULES) if raw["matched"][i, r]
                    ]
                matched_rules.sort(key=lambda t: t[1], reverse=True)
                for rank, (rule, score) in enumerate(matched_rules, start=1):
                    hyps.append(Hypothesis(
                        id=uuid4(), incident_id=uid, category=rule.category,
                        title=rule.name, description=rule.description,
                        confidence=rule.confidence, final_score=score, rank=rank,
                        support_count=len(rule.conditions),
                        signal_strength=rule.evidence_strength,
                        recommended_actions=rule.recommended_actions,
                        rule_id=rule.id, backend="tpu",
                        generated_by=HypothesisSource.RULES_ENGINE,
                    ))
            else:
                from .cpu_backend import _unknown_hypothesis
                from .signals import Signals
                h = _unknown_hypothesis(uid, Signals())
                h.backend = "tpu"
                hyps = [h]
            out.append(RCAResult(
                incident_id=uid, hypotheses=hyps, top_hypothesis=hyps[0],
                rules_matched=[h.rule_id for h in hyps if h.rule_id != "unknown"],
                backend="tpu",
            ))
        return out


def _incident_uuid(node_id: str) -> UUID:
    try:
        return UUID(node_id.split(":", 1)[1])
    except (ValueError, IndexError):
        return uuid4()
