"""graft-shield: crash-consistent recovery + graceful degradation for the
donated serving state.

PR 5 donated the resident mirror through the jitted ticks — fast, and
fragile: a device fault, a poisoned delta, or an executor crash mid-tick
destroys the ONLY copy of the state, and the sole fallback was a full
``_rebuild()`` (re-tensorize the world, drop every in-flight tick). The
reference platform got durability for free from Temporal; this layer
reproduces that bar for the device-resident scorer with three pillars:

1. **Crash-consistent recovery.** Every store-journal record batch is
   appended to a host-side write-ahead journal (rca/journal.py — fsync'd,
   crc-framed, O(delta)) BEFORE it is applied to the donated state, and
   the full resident state snapshots every N generation boundaries (the
   double-buffered queue makes the post-rescore boundary a natural atomic
   point: pending drained, in-flight superseded). Recovery = load last
   snapshot + replay the journal suffix through the SAME mutation code
   path serving uses (``_apply_records``/``_apply_edge_records``), which
   reproduces row allocation, widths, and device state bit-identically —
   and strictly cheaper than ``_rebuild()``.

2. **Deterministic fault injection.** rca/faults.py drives every stage of
   the tick pipeline from seeded schedules; tests/test_shield.py proves
   recovery parity under each fault class and under randomized schedules
   at pipeline depths 1 and 2.

3. **Graceful-degradation ladder + watchdog.** Transient faults get
   bounded retry with seeded-jitter exponential backoff (the
   workflow/engine.RetryPolicy semantics); persistent ones walk the
   ladder: kernel fallback (Pallas→XLA — bit-identical, PR 4), pipeline
   fallback (async depth-N → sync depth-1 — bit-identical, PR 5),
   journal-replay recovery, full store-derived rebuild, and finally (GNN
   only) fallback to the rules scorer. Every transition is counted in
   observability/metrics.py and surfaced in the rescore() result. A
   finite guard rejects NaN/inf verdicts before they serve: the staged
   batch is journaled as quarantined and the tick replays from
   store-truth state (the poison lived in the staged values, never in
   the store).

Fault-stage semantics (what a bare retry may assume): ``staging``,
``journal_append``, ``snapshot_write`` and ``fetch`` faults leave the
resident state coherent — an empty re-tick re-serves it, so bounded retry
is sound. ``dispatch``/``execute`` faults mean the drained deltas or the
donated buffers themselves are gone; every ladder step taken for those is
paired with a journal replay, because no configuration change can restage
lost state.

The snapshot fetch/restore kernels (``_snapshot_pack``/``_snapshot_unpack``)
are registered audit entrypoints (analysis/registry.py) with an explicit
zero-collective CostSpec: the recovery path is pinned by the same
graft-audit/cost substrate as the serving path, not trusted.
"""
from __future__ import annotations

import os
import pickle
import threading
import time
import uuid
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from ..config import Settings, get_settings
from ..ingestion.admission import CircuitBreaker
from ..observability import get_logger
from ..observability import metrics as obs_metrics
from ..observability import scope as obs_scope
from ..workflow.engine import NonRetryableError, RetryPolicy
from .journal import DeltaJournal
from .streaming import NonFiniteDelta

log = get_logger("shield")

# fault stages after which the resident state is still coherent — see
# module docstring; everything else is state-suspect
_RETRIABLE_STAGES = frozenset(
    {"staging", "journal_append", "snapshot_write", "fetch"})

# the degradation ladder, in escalation order. graft-heal slots the
# ``mesh_heal`` rung between journal replay and the full rebuild: once
# the per-shard classifier (rca/heal.ShardHealthTracker) has declared a
# mesh position persistently failed, replaying onto the SAME mesh is
# futile — the state re-places onto a survivor mesh at D' < D instead,
# strictly cheaper than the store-derived rebuild.
LADDER = ("kernel_fallback", "sync_depth1", "journal_replay", "mesh_heal",
          "full_rebuild", "rules_fallback")


@jax.jit
def _snapshot_pack(*arrays):
    """Pack the resident device buffers into ONE flat int32 buffer for the
    snapshot fetch: float tables bitcast to int32 (bit-exact, NaN payloads
    included), everything raveled and concatenated — so a snapshot pays a
    single device→host transfer regardless of how many mirrors the scorer
    carries (the dev tunnel charges per transfer, same economics as the
    packed tick delta)."""
    flat = []
    for a in arrays:
        if jnp.issubdtype(a.dtype, jnp.floating):
            a = jax.lax.bitcast_convert_type(a, jnp.int32)
        flat.append(a.reshape(-1))
    return jnp.concatenate(flat)


@partial(jax.jit, static_argnames=("layout",))
def _snapshot_unpack(flat, layout):
    """Inverse of :func:`_snapshot_pack`. ``layout`` is a static tuple of
    ``(shape, dtype_name)`` pairs recorded at pack time; float buffers are
    bitcast back, so restore is bit-exact."""
    out = []
    off = 0
    for shp, dt in layout:
        n = 1
        for d in shp:
            n *= d
        seg = flat[off:off + n].reshape(shp)
        off += n
        if dt == "float32":
            seg = jax.lax.bitcast_convert_type(seg, jnp.float32)
        out.append(seg)
    return tuple(out)


class NonFiniteVerdict(RuntimeError):
    """The finite guard rejected a verdict fetch: serving NaN/inf to the
    workflow would poison hypotheses, approvals, and remediation scoring
    downstream. Treated as state-suspect (the poison already scattered
    into the donated state), so recovery replays from store truth."""

    stage = "verdict"


class ShieldedScorer:
    """Fault-tolerance wrapper around a resident Streaming/GnnStreaming
    scorer: write-ahead journaling, periodic snapshots, watchdog, bounded
    retry, and the degradation ladder. Unknown attributes delegate to the
    wrapped scorer, so the workflow worker and tests use it as a drop-in.

    Serving drivers must mutate the STORE and let the shield drain the
    store journal (``serve()``/``rescore()``/``tick()``/``sync()``) — the
    write-ahead journal can only cover what flows through it; direct
    scorer mutation calls bypass durability (the bench's raw hot-loop
    mode does this deliberately and is documented as unshielded).
    """

    def __init__(self, scorer, settings: "Settings | None" = None,
                 directory: "str | None" = None, injector=None) -> None:
        self.scorer = scorer
        self.settings = settings or get_settings()
        self.injector = injector
        scorer.fault_injector = injector
        scorer.finite_delta_guard = True
        d = directory or self.settings.shield_dir or os.path.join(
            ".kaeg_shield", str(os.getpid()))
        # flight-recorder dumps land next to the journal they explain
        # (unless the operator routed them elsewhere): recovery forensics
        # and recovery state travel together
        self.flight_dir = (getattr(self.settings, "scope_flight_dir", "")
                           or os.path.join(d, "flight"))
        self.journal = DeltaJournal(
            d, fault_hook=injector.journal_hook if injector else None,
            fsync_every=getattr(self.settings,
                                "shield_wal_fsync_every_ticks", 1))
        self.retry = RetryPolicy(
            max_attempts=max(int(self.settings.shield_retry_attempts), 0),
            initial_interval_s=float(self.settings.shield_retry_backoff_s),
            backoff=2.0, max_interval_s=5.0)
        self.snapshot_every = max(
            int(self.settings.shield_snapshot_every_ticks), 1)
        self.tick_timeout_s = float(self.settings.shield_tick_timeout_s)
        self._lock = threading.RLock()
        # store-lineage token: a snapshot only restores onto the store it
        # was captured from (stamped on the store object; files from a
        # different lineage are ignored and recovery falls back to rebuild)
        store = scorer.store
        tok = getattr(store, "_shield_epoch", None)
        if tok is None:
            tok = uuid.uuid4().hex
            store._shield_epoch = tok
        self._epoch = tok
        # observability / test surface
        self.tier = "steady"
        self.tier_log: list[str] = []
        self.snapshots = 0
        self.recoveries = 0
        self.replayed_records = 0
        self.quarantined_batches = 0
        self.watchdog_trips = 0
        self.last_recovery_seconds = 0.0
        self._journal_seconds = 0.0
        self.journal_seconds_total = 0.0
        self._ticks_since_snapshot = 0
        self._last_batch = (0, 0)
        self._fallback_from = None      # the GNN scorer rules_fallback shed
        self._snap_thread: "threading.Thread | None" = None
        self.last_capture_seconds = 0.0
        self.last_snapshot_seconds = 0.0
        # graft-storm: circuit breaker around device dispatch. Bounded
        # consecutive dispatch-class failures open it; while open the
        # NON-verdict submission paths (tick()/absorb()) skip the device
        # entirely — the deltas wait in the store journal, whose cursor
        # the skipped drain never advanced, so crash recovery stays
        # sound — and the half-open probe after the cooldown re-walks
        # the full path once. The verdict boundary (rescore/serve) never
        # consults it: correctness beats fail-fast where a caller is
        # actually waiting on a verdict.
        self.breaker = CircuitBreaker(
            "dispatch",
            failure_threshold=getattr(self.settings,
                                      "breaker_failure_threshold", 5),
            cooldown_s=getattr(self.settings, "breaker_cooldown_s", 2.0))
        self.breaker_skips = 0
        self._last_run_failures = 0
        # graft-heal: per-shard health classification + live-reshard
        # bookkeeping. ``_mesh_home`` is the shard count the scorer was
        # built at (the re-expansion target); ``_mesh_excluded`` the
        # global device indices currently healed AROUND; ``_heal_gen``
        # the monotonic generation every journaled mesh_heal record
        # carries (compaction and replay order key on it, exactly the
        # params_swap discipline).
        from .heal import ShardHealthTracker
        self.health = ShardHealthTracker(
            failure_threshold=getattr(
                self.settings, "mesh_shard_failure_threshold", 3),
            cooldown_s=getattr(self.settings, "mesh_heal_cooldown_s", 5.0))
        self._mesh_home = scorer._graph_size()
        self._mesh_excluded: tuple[int, ...] = ()
        self._heal_gen = 0
        self.heals = 0
        self.reexpansions = 0
        self.attest_repairs = 0
        self.last_heal_seconds = 0.0
        # graft-swell: load-driven scale events through the same WAL seam
        self.scale_events = 0
        self.last_scale_seconds = 0.0

    # -- delegation --------------------------------------------------------

    def __getattr__(self, name):
        try:
            scorer = object.__getattribute__(self, "scorer")
        except AttributeError:
            raise AttributeError(name) from None
        return getattr(scorer, name)

    # -- protected serving API --------------------------------------------

    def serve(self, newest: bool = False) -> dict:
        """Journal + sync + rescore under the shield lock. Callers are
        serialized here (the shield must observe every failure), so each
        caller's prior store writes are drained by its own staging pass —
        the same visibility guarantee scorer.serve()'s generation protocol
        gives concurrent callers. ``newest=True`` (the async workflow
        verdict path, graft-surge) prefers the scorer's deferred
        newest-tick fetch — bit-identical, and the finite guard runs on
        the fetched result either way."""
        return self.rescore(newest=newest)

    def rescore(self, newest: bool = False) -> dict:
        with self._lock:
            self._maybe_reexpand()
            if newest:
                return self._run_with_recovery(
                    lambda: self._tick_rescore(newest=True))
            return self._run_with_recovery(self._tick_rescore)

    def tick(self) -> dict:
        """Protected pipelined submission (scorer.tick_async), behind the
        dispatch circuit breaker: while open, the submission is skipped
        outright — one state check per webhook instead of a ladder walk
        per webhook — and the deltas stay in the store journal for the
        half-open probe (or any verdict-boundary call) to drain."""
        with self._lock:
            self._maybe_reexpand()
            if not self.breaker.allow():
                return self._breaker_skip()
            try:
                out = self._run_with_recovery(self._tick_async)
            except (RuntimeError, OSError) as exc:
                if self.breaker.state == "open":
                    # the ladder exhausted AND the breaker just opened:
                    # the ingest path degrades to journal-only instead
                    # of surfacing a timeout per webhook
                    log.error("tick_degraded_breaker_open",
                              error=str(exc))
                    return {"dispatched": False, "breaker_open": True,
                            "error": str(exc)}
                raise
            if self._last_run_failures == 0:
                # a clean pass closes a half-open probe / resets the
                # consecutive-failure count; a pass that only succeeded
                # through recovery leaves the breaker where it was
                self.breaker.record_success()
            return out

    def _breaker_skip(self) -> dict:
        self.breaker_skips += 1
        backlog = 0
        fn = getattr(self.scorer, "_journal_backlog", None)
        if fn is not None:
            backlog = int(fn())
        return {"dispatched": False, "breaker_open": True,
                "backlog": backlog}

    def absorb(self) -> dict:
        """Protected webhook-burst ingestion (graft-surge): WAL-journal +
        apply the delta batch, then a pipelined tick submission. MUST
        shadow the scorer's absorb() — a ``__getattr__`` fallthrough
        would drain the store journal without write-ahead logging it,
        silently breaking crash recovery."""
        return self.tick()

    def swap_params(self, params, source: str = "") -> int:
        """graft-evolve: hot checkpoint swap, WAL-journaled BEFORE it is
        applied (the crash-consistency invariant — same order as delta
        batches). MUST shadow the scorer's swap_params: a ``__getattr__``
        fallthrough would swap without a journal record and recovery
        would replay post-swap deltas onto the pre-swap generation. The
        record carries the params LEAVES themselves (a few hundred KB,
        swaps are rare), so replay restores the exact swapped values
        bit-for-bit without depending on a checkpoint file that may have
        been pruned. Returns the new generation."""
        with self._lock:
            s = self.scorer
            gen = int(getattr(s, "params_generation", 0)) + 1
            leaves = [np.asarray(x)
                      for x in jax.tree_util.tree_leaves(params)]
            seq = int(s._synced_seq)
            self.journal.append((), seq, seq, kind="params_swap",
                                force_sync=True, generation=gen,
                                leaves=leaves, source=source)
            s.swap_params(params, generation=gen, source=source)
            obs_scope.FLIGHT_RECORDER.note_event(
                "params_swap_journaled", generation=gen, seq=seq)
            return gen

    def rollback_params(self) -> "int | None":
        """Journaled rollback to the previous generation (post-swap
        nonfinite/regression). The restored tree is re-journaled as a
        fresh swap record so replay ordering stays monotonic."""
        with self._lock:
            s = self.scorer
            prev = getattr(s, "_params_prev", None)
            if prev is None:
                return None
            # graft-audit: allow[wal-order] rollback applies FIRST so the journal records the exact restored leaves as a fresh swap record; crash in the gap replays the pre-rollback swap, and the nonfinite backstop that triggered us re-fires
            gen = s.rollback_params()
            if gen is None:
                return None
            leaves = [np.asarray(x)
                      for x in jax.tree_util.tree_leaves(s._params)]
            seq = int(s._synced_seq)
            self.journal.append((), seq, seq, kind="params_swap",
                                force_sync=True, generation=gen,
                                leaves=leaves, source=prev[2],
                                rollback=True)
            return gen

    def _replay_params_swap(self, batch) -> None:
        """Apply one WAL ``params_swap`` record during recovery: newer
        generations than the restored state re-install their exact
        leaves; older ones are already reflected in the snapshot."""
        s = self.scorer
        gen = int(batch.meta.get("generation", 0))
        if gen <= int(getattr(s, "params_generation", 0)):
            return
        treedef = jax.tree_util.tree_structure(s._params)
        params = jax.tree_util.tree_unflatten(
            treedef, list(batch.meta["leaves"]))
        s._swap_params_locked(params, gen,
                              source=batch.meta.get("source", ""))

    def sync(self) -> dict:
        """Journal + apply only (no dispatch) — for drivers that tick
        elsewhere."""
        with self._lock:
            return self._run_with_recovery(self._stage_and_apply)

    # -- the guarded tick --------------------------------------------------

    def _tick_rescore(self, newest: bool = False) -> dict:
        self._stage_and_apply()
        out = (self.scorer.rescore_newest() if newest
               else self.scorer.rescore())
        self._finite_guard(out)
        self._ticks_since_snapshot += 1
        if self._ticks_since_snapshot >= self.snapshot_every:
            self.snapshot_now(background=True)
        # recovery visibility in the rescore timing splits
        out["shield_tier"] = self.tier
        out["journal_seconds"] = self._journal_seconds
        out["recovery_seconds"] = self.last_recovery_seconds
        out["recoveries"] = self.recoveries
        out["quarantined_batches"] = self.quarantined_batches
        out["watchdog_trips"] = self.watchdog_trips
        return out

    def _tick_async(self) -> dict:
        self._stage_and_apply()
        out = self.scorer.tick_async()
        self._ticks_since_snapshot += 1
        if self._ticks_since_snapshot >= self.snapshot_every:
            self.snapshot_now(background=True)
        return out

    def _stage_and_apply(self) -> dict:
        """Drain the store journal, write-ahead the batch (fsync, BEFORE
        any state mutation — the crash-consistency invariant), then apply
        it through the scorer's mutation path."""
        s = self.scorer
        if self.injector is not None:
            self.injector.at("staging", s)
        recs, seq, truncated = s.store.journal_since(s._synced_seq)
        if truncated:
            # the bounded store journal evicted unseen records: only a
            # store-derived rebuild is sound (same fallback as sync())
            self._transition("full_rebuild")
            s._rebuild()
            obs_metrics.SHIELD_RECOVERIES.inc(mode="full_rebuild")
            self.recoveries += 1
            self._ticks_since_snapshot = self.snapshot_every
            s.syncs += 1
            return {"applied": 0, "rebuilt": True}
        lo = s._synced_seq
        if recs:
            t0 = time.perf_counter()
            nbytes = self.journal.append(recs, lo, seq)
            self._journal_seconds = time.perf_counter() - t0
            self.journal_seconds_total += self._journal_seconds
            obs_metrics.SHIELD_JOURNAL_BYTES.inc(float(nbytes))
        self._last_batch = (lo, seq)
        res = s._apply_records(recs)
        s.syncs += 1
        if res.get("rebuilt"):
            # _init_from_store re-derived everything from the store and
            # advanced the cursors past this batch; the pre-rebuild
            # snapshot is stale — refresh at the next boundary
            self._ticks_since_snapshot = self.snapshot_every
            return res
        s._synced_seq = max(seq, s._synced_seq)
        if hasattr(s, "_apply_edge_records"):
            s._apply_edge_records(recs)
            s._gnn_seq = max(seq, s._gnn_seq)
        return res

    def _finite_guard(self, out: dict) -> None:
        for k in ("probs", "scores", "top_score", "top_confidence"):
            v = out.get(k)
            if v is None:
                continue
            a = np.asarray(v)
            if a.dtype.kind == "f" and not np.isfinite(a).all():
                obs_metrics.SHIELD_NONFINITE_VERDICTS.inc(path="shield")
                raise NonFiniteVerdict(f"non-finite verdict field {k!r}")

    # -- failure handling: retry + degradation ladder ----------------------

    # a guarded call walks the ladder at most this many times before the
    # failure surfaces: a fault persisting through repeated recoveries,
    # rebuilds, and (for GNN) the rules fallback is an outage, not a blip
    MAX_LADDER_ROUNDS = 3

    def _run_with_recovery(self, fn):
        """Run one guarded operation; failures walk the bounded-retry +
        degradation ladder until the operation succeeds or the ladder is
        exhausted. Watchdog checks the successful path's wall time."""
        state = {"applied": set(), "rounds": 0, "failures": 0}
        while True:
            t0 = time.perf_counter()
            try:
                out = fn()
            except Exception as exc:
                state["failures"] += 1
                self._escalate(exc, state)
                continue
            self._watchdog(time.perf_counter() - t0)
            self._last_run_failures = state["failures"]
            if state["failures"] == 0:
                # a CLEAN pass (not one that limped through recovery):
                # transient shard faults reset, half-open probes close —
                # the transient/persistent distinction the classifier
                # draws (rca/heal.py)
                self.health.record_clean_pass()
            if state["failures"] and self.tier not in ("rules_fallback",
                                                       "breaker_open"):
                self.tier = "steady"
                self.scorer._scope_tier = "steady"
            return out

    def _escalate(self, exc: Exception, state: dict) -> None:
        """Pick and apply the next ladder step for this failure. Raises
        when the error is non-retryable (programming errors must surface,
        not degrade) or the ladder rounds are exhausted."""
        if isinstance(exc, (ValueError, TypeError, NonRetryableError)):
            raise exc
        stage = getattr(exc, "stage", "")
        suspect = stage not in _RETRIABLE_STAGES
        shard = getattr(exc, "shard", None)
        if shard is not None:
            # graft-heal: the fault is localized to ONE mesh position —
            # feed the per-shard classifier (N consecutive failures on
            # one position open its breaker = persistently failed shard,
            # which flips the ladder from replay to mesh_heal)
            self.health.record_failure(int(shard))
        if stage in ("dispatch", "execute", "pack", ""):
            # dispatch-class (or unattributed device-path) failure feeds
            # the circuit breaker; crossing the consecutive-failure
            # threshold opens it and becomes a visible shield tier
            was_open = self.breaker.state == "open"
            self.breaker.record_failure()
            if self.breaker.state == "open" and not was_open:
                self._transition("breaker_open")
        log.warning("guarded_tick_failed", stage=stage or "unknown",
                    error=str(exc), failures=state["failures"],
                    suspect=suspect)
        # forensic interleave: the failure lands in the flight ring at
        # its arrival order, so a dump shows WHICH tick records surround
        # the fault and what the pipeline looked like when it hit
        obs_scope.FLIGHT_RECORDER.note_event(
            "guarded_tick_failed", stage=stage or "unknown",
            error=f"{type(exc).__name__}: {str(exc)[:200]}",
            failures=state["failures"], suspect=suspect)
        if isinstance(exc, (NonFiniteVerdict, NonFiniteDelta)):
            # NonFiniteDelta: poison caught at the dispatch boundary
            # before the scatter; NonFiniteVerdict: the backstop at the
            # fetch boundary (e.g. silent device corruption). Both
            # quarantine the offending batch — replay serves store truth.
            lo, hi = self._last_batch
            self.journal.mark_quarantined(lo, hi, reason=str(exc))
            self.quarantined_batches += 1
            obs_metrics.SHIELD_QUARANTINED_DELTAS.inc()
            obs_scope.FLIGHT_RECORDER.note_event(
                "quarantined", seq_lo=lo, seq_hi=hi,
                reason=str(exc)[:200])
        if isinstance(exc, NonFiniteVerdict) and \
                getattr(self.scorer, "_params_prev", None) is not None:
            # graft-evolve: non-finite verdicts right after a hot
            # checkpoint swap indict the FRESHEST config change first —
            # roll the swap back (journaled, one-deep) and retry on the
            # restored generation before walking the heavier ladder. If
            # the rollback doesn't cure it, _params_prev is now None and
            # the next failure escalates normally — bounded by design.
            if self.rollback_params() is not None:
                # (the scorer's rollback already counts itself in
                # aiops_learn_rollbacks_total)
                self._transition("params_rollback")
                return
        if not suspect and state["failures"] <= self.retry.max_attempts:
            # transient, state coherent: bounded retry with seeded-jitter
            # backoff (key = store lineage + batch, so concurrent shields
            # de-synchronize while a replay of one shield sleeps the same)
            self._transition("retry")
            time.sleep(self.retry.delay(
                state["failures"],
                key=f"{self._epoch}:{self._last_batch[1]}"))
            return
        applied = state["applied"]
        while True:
            for step in LADDER:
                if step in applied:
                    continue
                applied.add(step)
                if self._apply_ladder_step(step, suspect):
                    return
            # every rung tried this round: a dense fault schedule may
            # outlast one pass (recoveries restore a consistent state, so
            # re-walking the ladder is sound) — but only boundedly
            state["rounds"] += 1
            if state["rounds"] >= self.MAX_LADDER_ROUNDS:
                raise exc
            applied.clear()

    def _apply_ladder_step(self, step: str, suspect: bool) -> bool:
        """Apply one degradation rung; False = not applicable here (or
        recovery unavailable), caller escalates to the next rung.
        State-suspect failures pair every configuration-only rung with a
        journal replay — no config change can restage lost deltas."""
        if step == "kernel_fallback":
            # graft-tide/graft-fuse: the DMA streaming tick sits at the
            # TOP of this rung — dma → fused → composed (Pallas) → XLA
            # (PR 4 / PR 14 / PR 16): degrading the lowering can change
            # which kernel faults, never verdicts (the f32 hops are
            # bit-identical; a quantized tier degrades with its table —
            # the resident tiers read the f32 features, so the fallback
            # verdict is the f32 one the tolerance contract is gated on)
            if getattr(self.scorer, "_use_dma", False):
                self.scorer._use_dma = False
            elif getattr(self.scorer, "_use_fused", False):
                self.scorer._use_fused = False
            elif getattr(self.scorer, "_use_pallas", False):
                self.scorer._use_pallas = False
            else:
                return False
            self._transition(step)
            if suspect:
                self._try_recover()
            return True
        if step == "sync_depth1":
            if self.scorer.pipeline_depth <= 1:
                return False
            # depth parity is bit-identical (PR 5): dropping to the
            # serialized loop narrows the blast radius of device faults
            # to one tick without changing results
            self.scorer.pipeline_depth = 1
            self.scorer._supersede_inflight()
            self._transition(step)
            if suspect:
                self._try_recover()
            return True
        if step == "journal_replay":
            if self._heal_ready() is not None:
                # a mesh position is CLASSIFIED persistently failed:
                # replaying bit-identical state onto the same dying
                # device is futile — fall through to the mesh_heal rung
                return False
            self._transition(step)
            return self._try_recover()
        if step == "mesh_heal":
            pos = self._heal_ready()
            if pos is None:
                return False
            self._transition(step)
            return self._try_heal(pos)
        if step == "full_rebuild":
            self._transition(step)
            self.scorer._rebuild()
            obs_metrics.SHIELD_RECOVERIES.inc(mode="full_rebuild")
            self.recoveries += 1
            self._ticks_since_snapshot = self.snapshot_every
            return True
        if step == "rules_fallback":
            if not self._engage_rules_fallback():
                return False
            self._transition(step)
            return True
        return False

    def _try_recover(self) -> bool:
        """Journal-replay recovery as a ladder step: a failure here (bad
        snapshot, injected fault mid-recovery) reports False so the
        caller escalates to the deeper tiers instead of wedging."""
        try:
            self.recover()
            return True
        except (RuntimeError, OSError, KeyError, pickle.PickleError) as exc:
            log.error("recovery_failed", error=str(exc))
            return False

    # -- graft-heal: live resharding + re-expansion ------------------------

    def _heal_enabled(self) -> bool:
        return bool(getattr(self.settings, "mesh_heal_enabled", True))

    def _heal_ready(self) -> "int | None":
        """Mesh position the classifier has declared persistently failed
        — or None (nothing classified / heal disabled / not sharded, in
        which case the existing replay/rebuild rungs apply unchanged)."""
        if not self._heal_enabled() or self.scorer._graph_size() <= 1:
            return None
        return self.health.failed_position()

    def _try_heal(self, pos: int) -> bool:
        """The mesh_heal rung body: a heal failure (no viable survivor
        layout, a placement error) reports False so the ladder escalates
        to the full rebuild instead of wedging — escalation IS the
        handling."""
        try:
            self.mesh_heal(positions=(int(pos),))
            return True
        except (RuntimeError, OSError, ValueError) as exc:
            log.error("mesh_heal_failed", error=str(exc))
            obs_scope.FLIGHT_RECORDER.note_event(
                "mesh_heal_failed", error=str(exc)[:200])
            return False

    def mesh_heal(self, positions: tuple[int, ...] = (),
                  exclude_devices: tuple[int, ...] = ()) -> dict:
        """Live D→D' resharding around failed hardware: WAL-journal the
        heal FIRST (crash-consistency — same order as delta batches and
        params swaps), then re-place the resident state onto a survivor
        mesh at the largest viable D' (rca/heal.plan_reshard) at a queue
        generation boundary. ``positions`` are CURRENT mesh positions
        (the classifier's verdicts — translated to global device indices
        here, since positions shift with every reshard); callers that
        already know the dead chip (benches, operators) pass
        ``exclude_devices`` directly. Returns the heal plan."""
        from . import heal as heal_mod
        s = self.scorer
        t0 = time.perf_counter()
        with s.serve_lock:
            d_old = s._graph_size()
            mesh_devs = (list(s.mesh.devices.flat)
                         if s.mesh is not None else [])
            dead = set(int(i) for i in exclude_devices)
            for pos in positions:
                if 0 <= int(pos) < len(mesh_devs):
                    dead.add(heal_mod.device_index(mesh_devs[int(pos)]))
            excluded = tuple(sorted(set(self._mesh_excluded) | dead))
            survivors = len(jax.devices()) - len(excluded)
            d_new = heal_mod.plan_reshard(
                s.snapshot.padded_nodes, d_old, survivors)
            seq = int(s._synced_seq)
            self._heal_gen += 1
            heal_gen = self._heal_gen   # captured under serve_lock: the
            # post-lock telemetry below must report THIS heal, not a
            # concurrent one that bumped the counter after release
            self.journal.append(
                (), seq, seq, kind="mesh_heal", force_sync=True,
                shards=d_new, exclude=excluded, from_shards=d_old,
                heal_gen=heal_gen)
            mesh = heal_mod.survivor_mesh(d_new, excluded)
            s.adopt_mesh(mesh)
            self._mesh_excluded = excluded
        for pos in positions:
            if 0 <= int(pos) < len(mesh_devs):
                self.health.exclude(
                    int(pos), heal_mod.device_index(mesh_devs[int(pos)]))
        self.heals += 1
        self.last_heal_seconds = time.perf_counter() - t0
        obs_metrics.MESH_HEALS.inc()
        obs_metrics.MESH_SERVING_SHARDS.set(float(max(d_new, 1)))
        obs_scope.FLIGHT_RECORDER.note_event(
            "mesh_heal", from_shards=d_old, to_shards=d_new,
            excluded=list(excluded), heal_gen=heal_gen)
        # the on-disk snapshot still carries the OLD mesh shape: force a
        # fresh one at the next generation boundary so recovery replays
        # at most one heal record
        self._ticks_since_snapshot = self.snapshot_every
        log.warning("mesh_healed", from_shards=d_old, to_shards=d_new,
                    excluded=excluded,
                    seconds=round(self.last_heal_seconds, 4))
        return {"from_shards": d_old, "shards": d_new,
                "excluded": excluded, "heal_gen": heal_gen}

    def _maybe_reexpand(self) -> None:
        """Half-open probe gate: once every excluded device's breaker has
        cooled down, grow D' back to the home mesh — the probe IS the
        next guarded tick. A clean pass closes the probing breakers; one
        more shard-localized failure re-opens and re-heals immediately."""
        # graft-audit: allow[lock-guard] advisory half-open gate: reexpand() re-checks _mesh_excluded under serve_lock; a stale read only delays the probe by one tick
        if (self._mesh_excluded and self._heal_enabled()
                and self.health.can_reexpand()):
            self.reexpand()

    def reexpand(self) -> "dict | None":
        """Grow D'→D at a queue generation boundary when the device
        returns (graft-evolve hot-swap discipline: in-flight ticks
        complete on the old mesh, superseded). WAL-journaled exactly like
        the heal, so crash-mid-expansion recovers to a consistent shard
        count. Returns the plan, or None when nothing is excluded."""
        from . import heal as heal_mod
        s = self.scorer
        with s.serve_lock:
            if not self._mesh_excluded:
                return None
            d_old = s._graph_size()
            d_new = self._mesh_home
            seq = int(s._synced_seq)
            self._heal_gen += 1
            heal_gen = self._heal_gen   # captured under serve_lock for
            # the post-lock telemetry, same as mesh_heal
            self.journal.append(
                (), seq, seq, kind="mesh_heal", force_sync=True,
                shards=d_new, exclude=(), from_shards=d_old,
                heal_gen=heal_gen, reexpand=True)
            mesh = heal_mod.survivor_mesh(d_new, ())
            s.adopt_mesh(mesh)
            excluded, self._mesh_excluded = self._mesh_excluded, ()
            mesh_devs = list(mesh.devices.flat) if mesh is not None else []
        dev_to_pos = {heal_mod.device_index(d): p
                      for p, d in enumerate(mesh_devs)}
        self.health.note_reexpanded(dev_to_pos)
        self.reexpansions += 1
        obs_metrics.MESH_REEXPANSIONS.inc()
        obs_metrics.MESH_SERVING_SHARDS.set(float(max(d_new, 1)))
        obs_scope.FLIGHT_RECORDER.note_event(
            "mesh_reexpand", from_shards=d_old, to_shards=d_new,
            probed=list(excluded), heal_gen=heal_gen)
        self._ticks_since_snapshot = self.snapshot_every
        log.warning("mesh_reexpanded", from_shards=d_old, to_shards=d_new,
                    probed=excluded)
        return {"from_shards": d_old, "shards": d_new,
                "probed": excluded, "heal_gen": heal_gen}

    def scale_mesh(self, target_shards: int) -> "dict | None":
        """graft-swell: LOAD-driven D→D' reshard through the exact seam
        graft-heal proved — WAL-journal first (the recovery replay treats
        it as one more ``mesh_heal`` record, no new replay path), then
        ``adopt_mesh`` at a queue generation boundary, keeping whatever
        devices the breaker currently excludes out of the new layout.
        The ElasticController pre-warms the target mesh before calling
        this, so the event pays an upload, never a compile. Also moves
        the elastic HOME: a later fault-heal + re-expansion returns to
        the load-chosen D', not the boot-time shard count. Returns the
        plan, or None when already at the target."""
        from . import heal as heal_mod
        s = self.scorer
        d_target = int(target_shards)
        t0 = time.perf_counter()
        with s.serve_lock:
            d_old = s._graph_size()
            if d_target == d_old:
                return None
            excluded = self._mesh_excluded
            if d_target < 1 or s.snapshot.padded_nodes % d_target:
                raise ValueError(
                    f"scale target {d_target} does not divide "
                    f"padded_nodes={s.snapshot.padded_nodes}")
            survivors = len(jax.devices()) - len(excluded)
            if d_target > survivors:
                raise RuntimeError(
                    f"scale target {d_target} exceeds {survivors} "
                    "non-excluded devices")
            seq = int(s._synced_seq)
            self._heal_gen += 1
            heal_gen = self._heal_gen   # captured under serve_lock for
            # the post-lock telemetry, same as mesh_heal
            self.journal.append(
                (), seq, seq, kind="mesh_heal", force_sync=True,
                shards=d_target, exclude=excluded, from_shards=d_old,
                heal_gen=heal_gen, scale=True)
            mesh = heal_mod.survivor_mesh(d_target, excluded)
            s.adopt_mesh(mesh)
            self._mesh_home = d_target
        direction = "up" if d_target > d_old else "down"
        self.scale_events += 1
        self.last_scale_seconds = time.perf_counter() - t0
        obs_metrics.MESH_SCALE_EVENTS.inc(direction=direction)
        obs_metrics.MESH_SERVING_SHARDS.set(float(max(d_target, 1)))
        obs_scope.FLIGHT_RECORDER.note_event(
            "mesh_scale", from_shards=d_old, to_shards=d_target,
            direction=direction, heal_gen=heal_gen)
        # same snapshot-forcing rule as the heal: the on-disk snapshot
        # still carries the OLD mesh shape
        self._ticks_since_snapshot = self.snapshot_every
        log.warning("mesh_scaled", from_shards=d_old,
                    to_shards=d_target, direction=direction,
                    seconds=round(self.last_scale_seconds, 4))
        return {"from_shards": d_old, "shards": d_target,
                "direction": direction, "heal_gen": heal_gen}

    def _attest_and_repair(self) -> tuple[int, ...]:
        """Per-shard state attestation at a snapshot generation boundary
        (rca/heal.attest_fold vs the host-truth oracle): SILENT per-shard
        corruption — the class the whole-state nonfinite backstop can
        only catch after it serves a wrong verdict — is detected here,
        localized to its shard, and repaired by re-uploading exactly the
        mismatched blocks from the host-truth mirrors (never a
        whole-state rebuild). Caller holds ``serve_lock``. Returns the
        mismatched shard positions; each one also feeds the shard-loss
        classifier (recurring silent corruption on one position is a
        failing device)."""
        if not getattr(self.settings, "mesh_attest", True):
            return ()
        from . import heal as heal_mod
        s = self.scorer
        if len(s._pending_feat):
            # staged-but-undrained deltas (a coalesced tick) mean the
            # host mirrors are LEGITIMATELY ahead of the device: a fold
            # now would false-flag healthy shards and feed the failure
            # classifier — attest at the next drained boundary instead
            return ()
        pairs = s._attest_arrays()
        g = max(s._graph_size(), 1) if s._graph_sharded(
            s.snapshot.padded_nodes, s.snapshot.padded_incidents) else 1
        dev = np.asarray(jax.device_get(heal_mod.attest_fold(
            *[getattr(s, attr) for attr, _host in pairs], shards=g)))
        host = heal_mod.attest_host([h for _a, h in pairs], g)
        mismatch = dev != host                     # [arrays, shards]
        bad = tuple(int(k) for k in np.flatnonzero(mismatch.any(axis=0)))
        if not bad:
            return ()
        for ai, (attr, truth) in enumerate(pairs):
            arr = getattr(s, attr)
            rows = arr.shape[0] // g
            for k in bad:
                if not mismatch[ai, k]:
                    continue
                block = np.ascontiguousarray(
                    np.asarray(truth)[k * rows:(k + 1) * rows])
                arr = arr.at[k * rows:(k + 1) * rows].set(
                    jnp.asarray(block, dtype=arr.dtype))
            setattr(s, attr, arr)
        s._apply_sharding()
        self.attest_repairs += 1
        for k in bad:
            obs_metrics.MESH_ATTEST_MISMATCH.inc(shard=str(k))
            self.health.record_failure(k)
        obs_metrics.MESH_ATTEST_REPAIRS.inc()
        obs_scope.FLIGHT_RECORDER.note_event(
            "attest_repair", shards=list(bad),
            arrays=[a for a, _h in pairs])
        log.warning("attest_repaired_shards", shards=bad)
        return bad

    def _watchdog(self, elapsed_s: float) -> None:
        if not self.tick_timeout_s or elapsed_s <= self.tick_timeout_s:
            return
        # an XLA dispatch cannot be cancelled host-side: the watchdog
        # bounds RECURRENCE — count the trip and drop to the serialized
        # depth-1 loop so at most one tick is ever exposed to a slow or
        # wedged device
        self.watchdog_trips += 1
        obs_metrics.SHIELD_WATCHDOG_TRIPS.inc()
        log.warning("watchdog_trip", elapsed_s=round(elapsed_s, 3),
                    timeout_s=self.tick_timeout_s)
        if self.scorer.pipeline_depth > 1:
            self.scorer.pipeline_depth = 1
            self.scorer._supersede_inflight()
            self._transition("sync_depth1")

    def _engage_rules_fallback(self) -> bool:
        """Last functional tier for a GNN scorer that cannot be revived:
        serve rules verdicts from a fresh StreamingScorer over the same
        store (shared result fields: top_rule_index / any_match /
        top_confidence). The faulting scorer is shed; the injector does
        NOT follow — the fallback must actually serve."""
        from .gnn_streaming import GnnStreamingScorer
        from .streaming import StreamingScorer
        if not isinstance(self.scorer, GnnStreamingScorer):
            return False
        old = self.scorer
        old.stop_warm(join=False)
        fallback = StreamingScorer(old.store, self.settings,
                                   now_s=old.now_s)
        fallback.finite_delta_guard = True
        self._fallback_from = old
        self.scorer = fallback
        self._ticks_since_snapshot = self.snapshot_every
        log.error("rules_fallback_engaged")
        return True

    def _transition(self, tier: str) -> None:
        self.tier = tier
        self.tier_log.append(tier)
        obs_metrics.SHIELD_TIER_TRANSITIONS.inc(tier=tier)
        # graft-scope: stamp the tier onto the scorer (every subsequent
        # TickSpan carries it) and freeze the flight ring to disk — the
        # forensic window AROUND the degradation, not just its counter
        self.scorer._scope_tier = tier
        obs_scope.FLIGHT_RECORDER.dump(f"tier:{tier}", self.flight_dir)

    # -- snapshots + recovery ---------------------------------------------

    def snapshot_now(self, background: bool = False) -> int:
        """Capture the full resident state (host bookkeeping + packed
        device arrays, ONE device→host transfer) and persist it
        atomically, then compact the WAL to the uncovered suffix.

        The CAPTURE is synchronous under serve_lock (a consistent cut of
        host + device state, ~O(resident bytes) of memcpy). With
        ``background=True`` (the cadence path) the persist — write +
        fsync + rename + compact, the disk-bound bulk of the cost — runs
        on a writer thread while serving continues; recovery and the next
        snapshot join it first. Returns bytes written (0 when deferred to
        the writer thread)."""
        self._join_snapshot_writer()
        s = self.scorer
        t0 = time.perf_counter()
        with s.serve_lock:
            # graft-heal: attest BEFORE the capture — a silently
            # corrupted shard block must be localized and repaired from
            # host truth here, never persisted into the recovery anchor
            self._attest_and_repair()
            arrays = s._resident_arrays()
            layout = tuple((tuple(int(d) for d in a.shape), str(a.dtype))
                           for a in arrays)
            flat = jax.device_get(_snapshot_pack(*arrays))
            host = pickle.dumps(s.capture_host_state(),
                                protocol=pickle.HIGHEST_PROTOCOL)
            store_seq = int(s._synced_seq)
            mesh_shards = s._graph_size()
        self.last_capture_seconds = time.perf_counter() - t0
        state = {"epoch": self._epoch, "store_seq": store_seq,
                 "klass": type(s).__name__, "layout": layout,
                 "flat": flat, "host": host,
                 # graft-evolve: the generation this snapshot serves —
                 # compaction uses it to drop only swap records the
                 # snapshot already reflects (the packed arrays carry the
                 # params values themselves)
                 "params_gen": int(getattr(s, "params_generation", 0)),
                 # graft-heal: the mesh shape the packed arrays were
                 # captured AT — recovery re-points the mesh before
                 # adopting, and compaction drops only heal records this
                 # snapshot already reflects (the params_swap discipline)
                 "mesh_shards": int(mesh_shards),
                 # graft-audit: allow[lock-guard] snapshot capture is serialized against heals/reexpands by the shield _lock, so the pair below is consistent
                 "mesh_exclude": tuple(self._mesh_excluded),
                 # graft-audit: allow[lock-guard] same shield-_lock serialization argument as mesh_exclude above
                 "heal_gen": int(self._heal_gen)}
        self.snapshots += 1
        self._ticks_since_snapshot = 0
        obs_metrics.SHIELD_SNAPSHOTS.inc()
        if background:
            self._snap_thread = threading.Thread(
                target=self._persist_snapshot, args=(state, t0),
                name="kaeg-shield-snapshot", daemon=False)
            self._snap_thread.start()
            return 0
        return self._persist_snapshot(state, t0)

    def _persist_snapshot(self, state: dict, t0: float) -> int:
        try:
            nbytes = self.journal.write_snapshot(state)
            self.journal.compact(state["store_seq"],
                                 through_params_gen=state["params_gen"],
                                 through_heal_gen=state.get("heal_gen"))
        except (OSError, RuntimeError) as exc:
            # a failed persist leaves the previous snapshot intact; the
            # next cadence (or recovery-time rebuild) covers the gap
            log.error("snapshot_persist_failed", error=str(exc))
            return 0
        self.last_snapshot_seconds = time.perf_counter() - t0
        log.info("snapshot_written", bytes=nbytes,
                 store_seq=state["store_seq"])
        return nbytes

    def _join_snapshot_writer(self) -> None:
        t = self._snap_thread
        if t is not None and t.is_alive():
            t.join()

    def recover(self) -> dict:
        """Load the last durable snapshot and replay the journal suffix —
        bit-identical to the pre-fault state, strictly cheaper than a
        rebuild. Falls back to the store-derived ``_rebuild()`` when no
        snapshot of this store lineage exists."""
        self._join_snapshot_writer()
        t0 = time.perf_counter()
        s = self.scorer
        state = self.journal.load_snapshot()
        if (state is None or state.get("epoch") != self._epoch
                or state.get("klass") != type(s).__name__):
            s._rebuild()
            dt = time.perf_counter() - t0
            self.recoveries += 1
            self.last_recovery_seconds = dt
            self._ticks_since_snapshot = self.snapshot_every
            obs_metrics.SHIELD_RECOVERIES.inc(mode="full_rebuild")
            log.warning("recovered_via_rebuild", seconds=round(dt, 4))
            obs_scope.FLIGHT_RECORDER.dump("recovery:full_rebuild",
                                           self.flight_dir)
            return {"mode": "full_rebuild", "replayed": 0, "seconds": dt}
        replayed = 0
        with s.serve_lock:
            # graft-heal: the packed arrays were captured at the
            # snapshot's mesh shape — re-point the mesh BEFORE adopting
            # so _apply_sharding places them at the layout they carry
            # (a crash between a heal and its covering snapshot restores
            # here, then replays the heal record below)
            from . import heal as heal_mod
            snap_shards = int(state.get("mesh_shards", s._graph_size()))
            snap_excl = tuple(state.get("mesh_exclude", ()))
            self._heal_gen = int(state.get("heal_gen", self._heal_gen))
            if (snap_shards != s._graph_size()
                    or snap_excl != self._mesh_excluded):
                s.mesh = heal_mod.survivor_mesh(snap_shards, snap_excl)
                self._mesh_excluded = snap_excl
            s.restore_host_state(pickle.loads(state["host"]))
            parts = _snapshot_unpack(jnp.asarray(state["flat"]),
                                     layout=state["layout"])
            s._adopt_resident(parts)
            batches, torn = self.journal.read()
            rb0 = s.rebuilds
            for b in batches:
                if b.kind == "mesh_heal":
                    # a heal/re-expansion journaled after the snapshot:
                    # re-apply it in file order so post-heal delta
                    # batches replay onto the shard count that actually
                    # served them — crash-mid-heal lands consistent
                    gen = int(b.meta.get("heal_gen", 0))
                    if gen <= self._heal_gen:
                        continue
                    excl = tuple(b.meta.get("exclude", ()))
                    s.adopt_mesh(heal_mod.survivor_mesh(
                        int(b.meta["shards"]), excl))
                    self._mesh_excluded = excl
                    self._heal_gen = gen
                    obs_scope.FLIGHT_RECORDER.note_event(
                        "mesh_heal_replayed", shards=int(b.meta["shards"]),
                        heal_gen=gen)
                    continue
                if b.kind == "params_swap":
                    # a swap journaled after the snapshot: re-install its
                    # exact leaves so post-swap deltas replay onto the
                    # generation that actually served them (file order ==
                    # live order — both appended under the shield lock)
                    if hasattr(s, "_swap_params_locked"):
                        self._replay_params_swap(b)
                    continue
                if b.kind != "deltas" or b.seq_hi <= s._synced_seq:
                    continue
                s._apply_records(b.recs)
                replayed += len(b.recs)
                if s.rebuilds != rb0:
                    # replay re-hit a bucket overflow: the rebuild is
                    # store-derived as of NOW, which supersedes the rest
                    break
                if hasattr(s, "_apply_edge_records"):
                    s._apply_edge_records(b.recs)
                    s._gnn_seq = max(b.seq_hi, s._gnn_seq)
                s._synced_seq = max(b.seq_hi, s._synced_seq)
        dt = time.perf_counter() - t0
        self.recoveries += 1
        self.replayed_records += replayed
        self.last_recovery_seconds = dt
        obs_metrics.SHIELD_REPLAYED_DELTAS.inc(float(replayed))
        obs_metrics.SHIELD_RECOVERIES.inc(mode="journal_replay")
        log.warning("recovered_via_journal_replay", replayed=replayed,
                    torn_truncated=torn, seconds=round(dt, 4))
        obs_scope.FLIGHT_RECORDER.dump("recovery:journal_replay",
                                       self.flight_dir)
        return {"mode": "journal_replay", "replayed": replayed,
                "torn_truncated": torn, "seconds": dt}

    def recover_or_snapshot(self) -> dict:
        """Scorer-acquisition hook (workflow/worker.py): restore from a
        compatible on-disk snapshot+journal if one exists for this store
        lineage, otherwise anchor a fresh snapshot so every later fault
        is recoverable from tick one."""
        with self._lock:
            state = self.journal.load_snapshot()
            if (state is not None and state.get("epoch") == self._epoch
                    and state.get("klass") == type(self.scorer).__name__):
                return self.recover()
            return {"mode": "fresh_snapshot", "bytes": self.snapshot_now()}

    def stats(self) -> dict:
        return {
            "tier": self.tier,
            "tier_log": tuple(self.tier_log),
            "snapshots": self.snapshots,
            "recoveries": self.recoveries,
            "replayed_records": self.replayed_records,
            "quarantined_batches": self.quarantined_batches,
            "watchdog_trips": self.watchdog_trips,
            "journal_batches": self.journal.appended_batches,
            "journal_bytes": self.journal.appended_bytes,
            "torn_truncations": self.journal.torn_truncations,
            "breaker": self.breaker.stats(),
            "breaker_skips": self.breaker_skips,
            "heals": self.heals,
            "reexpansions": self.reexpansions,
            "attest_repairs": self.attest_repairs,
            # graft-audit: allow[lock-guard] monitoring read — a tuple swap is atomic under the GIL and staleness is acceptable in stats output
            "mesh_excluded": self._mesh_excluded,
            "serving_shards": self.scorer._graph_size(),
            "shard_health": self.health.stats(),
        }

    def close(self) -> None:
        self._join_snapshot_writer()
        self.journal.close()
