"""GNN serving under churn — `rca_backend=gnn` on the streaming path.

VERDICT r4 ask 2: selecting the learned backend must not forfeit the
streaming/incremental serving architecture. `GnnStreamingScorer` extends
the resident `StreamingScorer` (rca/streaming.py) so the GNN shares its
device-resident feature matrix and O(change) bookkeeping, and adds the one
piece of state the rules fold never needed: a device-resident **edge
mirror** (the full COO the message passing consumes — CALLS/OWNS/
SCHEDULED_ON/..., both directions). The mirror carries the same
relation-bucketed layout as `build_snapshot` (static per-relation slice
offsets; see _mirror_init) so the tick runs the E-scaled bucketed kernel
— slots allocate from per-relation free lists, which keeps the static
offsets valid under churn, with a full re-mirror as the region-overflow
fallback. A full re-mirror additionally emits each slice dst-SORTED
(padding pinned to the last row), so post-rebuild ticks claim the
sorted-scatter fast path (`slices_sorted=True`) until the first in-place
edge churn reuses a slot and forfeits it — the promise is a per-state
fact tracked in `_slices_sorted`, not a hardcoded slow path.

Why a full re-embed per tick (not dirty-subgraph re-embedding): the GNN
forward is measured cheap at serving scale — a 3-layer forward over the
whole padded graph rides the same fused-tick dispatch and scores EVERY
incident at once, so per-tick cost is O(graph) device time (~ms) instead
of O(3-hop frontier) host bookkeeping; at the bench's 10k-pod world the
streaming rate stays well above the 1k ev/s target (bench.py config 4
emits the `backend=gnn` record). Dirty-frontier re-embedding would save
device-ms only once graphs outgrow HBM — the graph-sharded ring fold
(parallel/sharded_rules.py) is that escape hatch, not sparser ticks.

Mirror maintenance is **journal-driven**: the store journals every
mutation (graph/store.py `_jrec`), so the mirror drains the journal with
its OWN cursor at each dispatch. That covers both serving (workflow
writers → `serve()` → base `sync()`) and direct-mutation drivers (the
streaming bench calls scorer mutation methods itself and never `sync()`s)
with one code path. Node removals cascade edge removals WITHOUT per-edge
journal records (store `_remove_one` journals only `node-`), so the
mirror keeps a per-node adjacency of live edge keys. Row resolution
happens at drain time against the base scorer's `_id_to_idx`; an edge+
whose endpoint no longer resolves is an edge whose endpoint was removed
later in the same batch — the store cascade guarantees it is gone from
the final state too, so skipping it is exact, not lossy.

Reference analog: the traversal-then-score serving loop (neo4j.py:169-201
feeding the learned ranker) — here the traversal is the resident COO and
the score is one forward.
"""
from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from ..analysis import ladders as _ladders
from ..graph.store import EvidenceGraphStore
from ..observability import get_logger
from ..observability import metrics as obs_metrics
from ..observability import scope as obs_scope
from .ruleset import NUM_RULES
from .streaming import FeatureStage, StreamingScorer, _DELTA_BUCKETS
from . import gnn

log = get_logger("gnn_streaming")

_EdgeKey = tuple[str, str, int]   # (src_id, dst_id, kind) — store edge key


@partial(jax.jit, static_argnames=("pk", "ek", "pi", "rel_offsets",
                                   "slices_sorted", "compute_dtype",
                                   "pallas"),
         donate_argnums=(2, 3, 4, 5, 6, 7))
def _gnn_tick(params, features, kind, nmask, esrc, edst, erel, emask, ints,
              pk: int, ek: int, pi: int, rel_offsets=None,
              slices_sorted: bool = False, compute_dtype=None,
              pallas: bool = False):
    """Apply the packed aux/edge deltas to the resident arrays, then run
    the full forward. The resident mirror (kind/nmask + the four edge
    arrays) is DONATED — the caller replaces its handles with the
    returned buffers, so XLA applies the delta scatters in place instead
    of reallocating the whole mirror per tick (`tick-donation` audit
    rule). ``features`` is NOT donated: it is the base scorer's resident
    buffer and must survive this tick for the next rules tick. Warm
    paths pass stand-ins for the donated positions, never live handles.
    One int32 transfer carries every delta (the tunnel
    charges per-transfer latency — see streaming._tick):

      [ f_idx pk | kind_v pk | nmask_v pk |
        e_idx ek | e_src ek | e_dst ek | e_rel ek | e_mask ek |
        incident_nodes pi | incident_mask pi ]

    Masks ship as 0/1 ints and cast on device. Out-of-range indices (the
    padding of each delta) drop out. incident tables are tiny ([Pi]) and
    ship fresh each tick — no dirty tracking needed for arrivals/closures.
    The caller replaces its resident handles with the returned buffers."""
    f_idx = ints[:pk]
    kind_v = ints[pk:2 * pk]
    nmask_v = ints[2 * pk:3 * pk].astype(jnp.float32)
    o = 3 * pk
    e_idx = ints[o:o + ek]
    e_src = ints[o + ek:o + 2 * ek]
    e_dst = ints[o + 2 * ek:o + 3 * ek]
    e_rel = ints[o + 3 * ek:o + 4 * ek]
    e_mask = ints[o + 4 * ek:o + 5 * ek].astype(jnp.float32)
    o += 5 * ek
    inc_nodes = ints[o:o + pi]
    inc_mask = ints[o + pi:o + 2 * pi].astype(jnp.float32)

    kind = kind.at[f_idx].set(kind_v, mode="drop")
    nmask = nmask.at[f_idx].set(nmask_v, mode="drop")
    esrc = esrc.at[e_idx].set(e_src, mode="drop")
    edst = edst.at[e_idx].set(e_dst, mode="drop")
    erel = erel.at[e_idx].set(e_rel, mode="drop")
    emask = emask.at[e_idx].set(e_mask, mode="drop")

    logits = gnn.forward(params, features, kind, nmask,
                         esrc, edst, erel, emask, inc_nodes,
                         rel_offsets=rel_offsets,
                         slices_sorted=slices_sorted,
                         compute_dtype=compute_dtype,
                         pallas=pallas)
    probs = jax.nn.softmax(logits, axis=-1)
    # mask dead incident rows so a stale row can never surface a score
    probs = probs * inc_mask[:, None]
    return kind, nmask, esrc, edst, erel, emask, logits, probs


@partial(jax.jit, static_argnames=("pk", "ek", "pi", "rel_offsets",
                                   "compute_dtype"),
         donate_argnums=(2, 3, 4, 5, 6, 7))
def _gnn_fused_tick(params, features, kind, nmask, esrc, edst, erel, emask,
                    ints, pk: int, ek: int, pi: int, rel_offsets=None,
                    compute_dtype=None):
    """graft-fuse: the fused streaming tick (settings.gnn_fused_tick) —
    the SAME operand layout, donation contract and return tuple as
    :func:`_gnn_tick`, but delta scatter, message pass and score
    reduction all run inside ONE Pallas kernel
    (ops/pallas_segment.pallas_fused_gnn_tick): the [N, H] activations
    stay VMEM-resident across stages instead of round-tripping through
    HBM between the scatter, each message-pass layer and the readout.
    BIT-identical to the composed tick (the parity oracle) in f32;
    ``compute_dtype="bfloat16"`` (graft-tide) runs the matmul operands
    in bf16 with f32 accumulation — tolerance-gated against the f32
    oracle, same fold order. EDGE_TILE-aligned bucketed layouts only —
    the dispatcher keeps the composed tick for every other
    configuration."""
    from ..ops.pallas_segment import pallas_fused_gnn_tick
    return pallas_fused_gnn_tick(params, features, kind, nmask, esrc,
                                 edst, erel, emask, ints, pk=pk, ek=ek,
                                 pi=pi, rel_offsets=rel_offsets,
                                 compute_dtype=compute_dtype)


@partial(jax.jit, static_argnames=("pk", "ek", "pi", "rel_offsets",
                                   "node_block", "compute_dtype"),
         donate_argnums=(2, 3, 4, 5, 6, 7, 9, 10))
def _gnn_dma_tick(params, features, kind, nmask, esrc, edst, erel, emask,
                  ints, h_a, h_b, pk: int, ek: int, pi: int,
                  rel_offsets=None, node_block: int = 2048,
                  compute_dtype=None):
    """graft-tide: the beyond-VMEM streaming tick (settings.gnn_tick_dma)
    — same operand layout, delta semantics and leading 8-tuple as
    :func:`_gnn_tick`, but features, the edge mirror and the [N, H]
    activations stay HBM-resident and stream through double-buffered
    VMEM windows (ops/pallas_segment.pallas_fused_gnn_tick_dma). The
    donated set grows by the two ``h_a``/``h_b`` activation ping-pong
    buffers — pure per-tick scratch the scorer keeps across ticks
    (``_dma_h``) so they are never reallocated; they return as outputs
    8/9. ``features`` is NOT donated (the base scorer's resident f32
    buffer — the quantized tiers use :func:`_gnn_dma_tick_q` instead).
    f32 path bit-identical to the composed oracle; serving-only."""
    from ..ops.pallas_segment import pallas_fused_gnn_tick_dma
    return pallas_fused_gnn_tick_dma(
        params, features, kind, nmask, esrc, edst, erel, emask, ints,
        h_a, h_b, pk=pk, ek=ek, pi=pi, rel_offsets=rel_offsets,
        node_block=node_block, compute_dtype=compute_dtype)


@partial(jax.jit, static_argnames=("pk", "ek", "pi", "rel_offsets",
                                   "node_block", "compute_dtype",
                                   "feat_quant"),
         donate_argnums=(1, 2, 3, 4, 5, 6, 7, 9, 10))
def _gnn_dma_tick_q(params, features_q, kind, nmask, esrc, edst, erel,
                    emask, ints, h_a, h_b, fq_rows, feat_scale,
                    pk: int, ek: int, pi: int, rel_offsets=None,
                    node_block: int = 2048, compute_dtype=None,
                    feat_quant: str = "int8"):
    """graft-tide quantized tiers of :func:`_gnn_dma_tick`: the node
    feature table is the HBM-resident bf16/int8 mirror ``features_q``
    (DONATED — the per-tick ``fq_rows`` delta rows scatter into it
    in-kernel and the updated table returns as output 10, so the quant
    mirror flows through like the edge mirror does). ``feat_scale`` is
    the int8 per-column scale (None for bf16); embeds dequantize and
    accumulate in f32. Tolerance-gated vs the f32 oracle."""
    from ..ops.pallas_segment import pallas_fused_gnn_tick_dma
    return pallas_fused_gnn_tick_dma(
        params, features_q, kind, nmask, esrc, edst, erel, emask, ints,
        h_a, h_b, pk=pk, ek=ek, pi=pi, rel_offsets=rel_offsets,
        node_block=node_block, compute_dtype=compute_dtype,
        feat_quant=feat_quant, fq_rows=fq_rows, feat_scale=feat_scale)


class GnnStreamingScorer(StreamingScorer):
    """StreamingScorer + resident edge mirror + per-tick GNN forward.

    `rescore()`/`serve()` return the GnnRcaBackend raw-dict surface
    (incident_ids / probs / top_rule_index / any_match / top_confidence),
    so `get_backend("gnn").results(raw=...)` and the workflow path work
    unchanged. The base rules tick still runs (it applies the shared
    feature deltas and costs ~µs); its outputs simply are not fetched.
    """

    def __init__(self, store: EvidenceGraphStore, settings=None,
                 params: gnn.Params | None = None, mesh=None,
                 now_s: float | None = None) -> None:
        if params is None:
            from .gnn_backend import GnnRcaBackend
            # resolve the checkpoint from the settings THIS scorer was
            # given, not the global env-derived ones (code-review r5)
            params = GnnRcaBackend(settings=settings).params
        self._params = jax.tree_util.tree_map(jnp.asarray, params)
        # graft-evolve: previous (params, generation) kept for one swap so
        # a post-swap nonfinite/accuracy regression can roll back without
        # a checkpoint reload; _params_source names the checkpoint the
        # current generation came from ("" = constructor params)
        self._params_prev: "tuple | None" = None
        self._params_source = ""
        # graft-fleet: a mesh with a real ``graph`` axis is served by the
        # sharded GNN tick (parallel/sharded_streaming.sharded_gnn_tick:
        # per-shard edge regions, ring-halo message pass). A dp-only mesh
        # has no sharded-GNN mapping (incident readout is not dp-sharded
        # here) and falls back to single-device as before.
        if mesh is not None and ("graph" not in getattr(mesh, "axis_names", ())
                                 or mesh.shape["graph"] <= 1):
            log.warning("gnn_streaming_mesh_unsupported")
            mesh = None
        # kernel selection (set BEFORE super().__init__, which builds the
        # mirror): the mirror layout is relation-bucketed either way —
        # a valid COO for the reference kernel too — the flag only picks
        # which kernel the tick runs
        from ..config import get_settings
        cfg = settings or get_settings()
        self._use_bucketed = bool(getattr(cfg, "gnn_bucketed", True))
        self._compute_dtype = getattr(cfg, "gnn_compute_dtype", "") or None
        # Pallas serving tier on the STREAMING path too (settings.gnn_pallas):
        # bit-identical to the XLA kernel, so the shield's kernel-fallback
        # degradation tier (Pallas→XLA on repeated device faults) cannot
        # change verdicts — only the lowering that produces them
        self._use_pallas = bool(getattr(cfg, "gnn_pallas", False))
        # graft-fuse: the fused streaming tick (settings.gnn_fused_tick) —
        # delta scatter + message pass + score reduction in ONE Pallas
        # kernel. Sits ABOVE the pallas tier on the shield's
        # kernel-fallback rung: fused → composed(pallas/XLA) → XLA, every
        # hop bit-identical. f32 bucketed layouts only; the dispatcher
        # falls back to the composed tick otherwise (_fused_ok).
        self._use_fused = bool(getattr(cfg, "gnn_fused_tick", False))
        # graft-tide: the beyond-VMEM DMA streaming tier
        # (settings.gnn_tick_dma) — features, edge mirror and [N, H]
        # activations HBM-resident, streamed through double-buffered
        # VMEM windows. Auto-selected per dispatch once the resident
        # fused tick's closed-form VMEM demand exceeds the soft budget,
        # or whenever a quantized feature tier is on (_dma_ok). Sits
        # ABOVE fused on the shield's kernel-fallback rung:
        # dma → fused → composed(Pallas/XLA) → XLA.
        self._use_dma = bool(getattr(cfg, "gnn_tick_dma", False))
        self._vmem_budget = int(getattr(cfg, "vmem_budget_bytes",
                                        8 * 2 ** 20))
        self._dma_node_block = int(getattr(cfg, "gnn_dma_node_block",
                                           _ladders.DMA_NODE_BLOCK))
        self._feat_quant = str(getattr(cfg, "gnn_feature_quant", "") or "")
        # persistent DMA activation ping-pong scratch (donated + rebound
        # every DMA tick — content is pure per-tick scratch, fully
        # rewritten in-kernel, so rebinding on warm shapes is safe)
        self._dma_h: "tuple | None" = None
        # quantized feature mirror (HBM-resident table + per-column
        # scale), re-derived deterministically from host truth at every
        # mirror (re)build / shield restore (_quant_refresh)
        self._features_q_dev = None
        self._feat_scale_dev = None
        self._feat_scale_host = None
        # transient per-dispatch stash: the quantized delta rows the next
        # DMA tick scatters into the quant table (consumed by
        # _dispatch_dma; zeros for warm calls)
        self._dma_stage_fq = None
        # transient per-dispatch stash: the packed GNN delta the staged
        # slab should carry (single-transfer satellite; see dispatch)
        self._gnn_stage = None
        super().__init__(store, settings, mesh=mesh, now_s=now_s)
        # graft-scope: this scorer's ticks and SLO samples are labeled by
        # the backend that actually produced the verdict
        self.scope.backend = "gnn"

    def _tick_statics(self, rel_offsets=None, slices_sorted=None) -> dict:
        """Static kwargs for _gnn_tick under the current mode. A fresh
        re-mirror lays every slice out dst-sorted, so post-rebuild ticks
        claim the sorted-scatter fast path; the first in-place edge churn
        reuses a slot, breaks within-slice order, and flips
        `_slices_sorted` off until the next re-mirror
        (_packed_gnn_delta). ``slices_sorted`` overrides the tracked
        state for warm pre-compiles of a specific variant."""
        offs = rel_offsets if rel_offsets is not None else self._rel_offsets
        ss = self._slices_sorted if slices_sorted is None else slices_sorted
        return {
            "rel_offsets": offs if self._use_bucketed else None,
            "slices_sorted": bool(ss) if self._use_bucketed else False,
            "compute_dtype": self._compute_dtype if self._use_bucketed
            else None,
            "pallas": self._use_pallas if self._use_bucketed else False,
        }

    def _fused_ok(self, rel_offsets=None) -> bool:
        """Whether the fused Pallas tick can serve the CURRENT (or given)
        layout: fused tier on, bucketed f32 (or, since graft-tide, bf16)
        math, a non-empty EDGE_TILE-aligned slice table, single-device
        mirror. Everything else keeps the composed tick — same verdicts,
        different lowering."""
        if not (self._use_fused and self._use_bucketed
                and self._compute_dtype in (None, "bfloat16")
                and not getattr(self, "_mirror_sharded", False)):
            return False
        from ..ops.pallas_segment import tiles_align
        offs = rel_offsets if rel_offsets is not None \
            else getattr(self, "_rel_offsets", ())
        return (len(offs) >= 2 and int(offs[-1]) > 0
                and tiles_align(offs))

    def _tick_vmem_demand(self, args: tuple, pk: int, ek: int,
                          pi: int) -> int:
        """Closed-form VMEM working set the RESIDENT fused tick would
        need for these operands (ops/pallas_segment.fused_tick_vmem_bytes)
        — the dispatcher compares it against settings.vmem_budget_bytes
        to auto-select the DMA streaming tier."""
        from ..ops.pallas_segment import fused_tick_vmem_bytes
        params, features = args[0], args[1]
        layers = params["layers"]
        return fused_tick_vmem_bytes(
            pn=int(features.shape[0]), pe=int(args[4].shape[0]),
            dim=int(features.shape[1]),
            hidden=int(params["embed_b"].shape[0]),
            classes=int(params["head_b"].shape[0]),
            num_kinds=int(params["kind_emb"].shape[0]),
            num_rels=int(layers[0]["w_rel"].shape[0]),
            num_layers=len(layers), pk=pk, ek=ek, pi=pi)

    def _dma_ok(self, args: tuple, pk: int, ek: int, pi: int,
                rel_offsets=None) -> bool:
        """Whether the DMA streaming tick serves these operands: DMA tier
        on, bucketed single-device layout, f32/bf16 compute, a non-empty
        EDGE_TILE-aligned slice table, and EITHER a quantized feature
        tier is selected (the quant table is HBM-resident by
        construction) OR the resident tick's closed-form VMEM demand
        exceeds the soft budget — small graphs keep the (cheaper, bit-
        identical) resident kernel."""
        if not (self._use_dma and self._use_bucketed
                and self._compute_dtype in (None, "bfloat16")
                and not getattr(self, "_mirror_sharded", False)):
            return False
        from ..ops.pallas_segment import tiles_align
        offs = rel_offsets if rel_offsets is not None \
            else getattr(self, "_rel_offsets", ())
        if not (len(offs) >= 2 and int(offs[-1]) > 0
                and tiles_align(offs)):
            return False
        pn = int(args[1].shape[0])
        if pn % min(self._dma_node_block, pn) != 0:
            return False
        if self._feat_quant:
            return True
        return self._tick_vmem_demand(args, pk, ek, pi) > self._vmem_budget

    def _dispatch_dma(self, args: tuple, pk: int, ek: int, pi: int,
                      offs, live: bool):
        """Run one DMA streaming tick. ``live`` marks a real dispatch:
        the persistent activation scratch (``_dma_h``) and, under a quant
        tier, the resident quant table are donated and rebound from the
        outputs; warm calls get same-aval stand-ins so they compile the
        exact serving executable without touching resident state."""
        params, features = args[0], args[1]
        pn = int(features.shape[0])
        dim = int(features.shape[1])
        hidden = int(params["embed_b"].shape[0])
        nb = min(self._dma_node_block, pn)
        h = self._dma_h if live else None
        if h is None or tuple(h[0].shape) != (pn, hidden):
            h = (jnp.zeros((pn, hidden), jnp.float32),
                 jnp.zeros((pn, hidden), jnp.float32))
        if live:
            self._dma_h = None   # donated below; rebound from the outputs
        if not self._feat_quant:
            out = _gnn_dma_tick(*args, *h, pk=pk, ek=ek, pi=pi,
                                rel_offsets=offs, node_block=nb,
                                compute_dtype=self._compute_dtype)
            if live:
                self._dma_h = (out[8], out[9])
            return out[:8]
        qdt = jnp.int8 if self._feat_quant == "int8" else jnp.bfloat16
        fq_rows = None
        if live:
            qtable, scale = self._features_q_dev, self._feat_scale_dev
            fq_rows, self._dma_stage_fq = self._dma_stage_fq, None
            self._features_q_dev = None   # donated; rebound below
        else:
            qtable = jnp.zeros((pn, dim), qdt)
            scale = (jnp.ones((dim,), jnp.float32)
                     if self._feat_quant == "int8" else None)
        if fq_rows is None or int(fq_rows.shape[0]) != pk:
            fq_rows = jnp.zeros((pk, dim), qdt)
        out = _gnn_dma_tick_q(params, qtable, *args[2:], *h, fq_rows,
                              scale, pk=pk, ek=ek, pi=pi, rel_offsets=offs,
                              node_block=nb,
                              compute_dtype=self._compute_dtype,
                              feat_quant=self._feat_quant)
        if live:
            self._features_q_dev = out[10]
            self._dma_h = (out[8], out[9])
        return out[:8]

    def _call_gnn_tick(self, args: tuple, pk: int, ek: int, pi: int,
                       rel_offsets=None, slices_sorted=None,
                       live: bool = False):
        """Run (or warm) ONE single-device GNN tick at the given shapes
        through the tier the settings select — the DMA streaming kernel
        when the operands outgrow VMEM (or a quant tier is on), the
        fused Pallas kernel when the layout admits it, the composed
        scatter→forward tick otherwise. Single seam so dispatch and
        every warm path compile exactly the variant serving will run.
        Returns the 8-tuple."""
        offs = rel_offsets if rel_offsets is not None \
            else self._rel_offsets
        if self._dma_ok(args, pk, ek, pi, offs):
            return self._dispatch_dma(args, pk, ek, pi, offs, live)
        if self._fused_ok(offs):
            return _gnn_fused_tick(*args, pk=pk, ek=ek, pi=pi,
                                   rel_offsets=offs,
                                   compute_dtype=self._compute_dtype)
        statics = self._tick_statics(rel_offsets=offs,
                                     slices_sorted=slices_sorted)
        return _gnn_tick(*args, pk=pk, ek=ek, pi=pi, **statics)

    def _staged_extra_ints(self):
        """graft-fuse single-transfer satellite: hand the packed GNN
        delta (prepared by dispatch() BEFORE the base tick stages) to
        the base scorer's columnar slab, so the GNN tick's ints ride
        the same host→device transfer as the base delta."""
        return self._gnn_stage

    # -- mirror (re)initialisation ---------------------------------------

    def _init_from_store(self) -> None:
        super()._init_from_store()
        # the base captured _synced_seq BEFORE tensorizing; the mirror is
        # built from the same store (records between the capture and now
        # replay idempotently at the next drain)
        self._gnn_seq = self._synced_seq
        self._mirror_init()

    def _mirror_graph_sharded(self) -> bool:
        """Whether a (re)mirror of the CURRENT shapes lands sharded."""
        return self._graph_sharded(self.snapshot.padded_nodes,
                                   self.snapshot.padded_incidents)

    def _mirror_offsets_now(self) -> tuple[int, ...]:
        """The relation-region offsets a re-mirror of the CURRENT store
        would derive — the single derivation shared by _mirror_init and
        warm_growth, so the warm pre-compiles the shapes a rebuild will
        actually land on. In graph-sharded mode these are the SHARED
        per-shard region capacities (max live count over shards, the
        partition.py contract): one static tuple describes every shard."""
        from ..graph.schema import RelationKind
        from ..graph.snapshot import REL_SLICE_BUCKETS, rel_slice_offsets
        num_rels = len(RelationKind)
        _, edges = self.store._raw()
        if self._mirror_graph_sharded():
            from ..parallel.sharded_streaming import shared_shard_offsets
            g = self._graph_size()
            nps = self.snapshot.padded_nodes // g
            counts = np.zeros((g, num_rels), np.int64)
            for e in edges:
                srow = self._id_to_idx.get(e.src)
                drow = self._id_to_idx.get(e.dst)
                if srow is None or drow is None:
                    continue
                # each direction lives on its DESTINATION's owner shard
                counts[drow // nps, int(e.kind)] += 1
                counts[srow // nps, int(e.kind)] += 1
            return shared_shard_offsets(counts, slack=1 / 3,
                                        min_cap=REL_SLICE_BUCKETS[0])
        counts = np.zeros(num_rels, np.int64)
        for e in edges:
            counts[int(e.kind)] += 2           # both directions
        # 1/3 growth slack per region + a minimum slice per relation so
        # first-edge churn of an unseen relation lands in a free pair
        # instead of forcing an immediate re-mirror
        return rel_slice_offsets(counts, slack=1 / 3,
                                 min_cap=REL_SLICE_BUCKETS[0])

    def _mirror_init(self) -> None:
        """Rebuild the edge mirror + aux device arrays from the store,
        resolving rows through the base scorer's CURRENT id->row map
        (NOT a fresh snapshot: rows must match the resident features).

        Relation-bucketed layout (the full graph/snapshot.py contract,
        INCLUDING the within-slice dst sort): relation r owns slice
        [off[r], off[r+1]) of the edge arrays; a re-mirror emits each
        slice's directed edges sorted by dst with padding pinned to the
        last node row, so the freshly-built layout satisfies the
        per-slice sorted promise and `_slices_sorted` flips on. Under
        churn, directed slots allocate individually from their OWN
        region's free list (sorting decouples an edge's fwd/rev entries,
        so slots are no longer adjacent pairs), which keeps the static
        offset table valid under arbitrary churn but forfeits the sorted
        promise at the first in-place delta; a region running out of
        slots falls back to a full re-mirror with re-derived capacities
        (counted in stats via the journal-truncation/rebuild paths that
        also call this).

        graft-fleet: in graph-sharded mode the slot space becomes D
        stacked per-shard region sets — shard g owns global slots
        [g·Pe_shard, (g+1)·Pe_shard) with the SHARED static offsets per
        relation (max live count over shards, the partition.py contract).
        Each directed entry lives on its DESTINATION row's owner shard
        and stores its dst SHARD-LOCAL (the tick's segment-sum is
        shard-local); src stays global (the ring assembly resolves it).
        The within-region fill keeps the same STABLE dst sort as the
        single-device layout, so a dst's edges keep store order in both —
        which is why a freshly-mirrored sharded tick is bit-identical to
        the single-device one (only slot REUSE under churn diverges the
        per-dst accumulation order, to float tolerance)."""
        from ..graph.schema import RelationKind
        offs = self._mirror_offsets_now()
        num_rels = len(RelationKind)
        pn = self.snapshot.padded_nodes
        self._mirror_sharded = self._mirror_graph_sharded()
        g = self._graph_size() if self._mirror_sharded else 1
        nps = pn // g
        pe_shard = max(int(offs[-1]), 1)
        self._pe_shard = pe_shard
        pe = pe_shard * g
        _, edges = self.store._raw()
        esrc = np.zeros(pe, np.int32)
        # padding dst pinned to the last (shard-local) row so the tail of
        # every slice keeps the sorted promise; masks zero it
        edst = np.full(pe, nps - 1, np.int32)
        erel = np.full(pe, -1, np.int32)
        emask = np.zeros(pe, np.float32)
        self._edge_slot: dict[_EdgeKey, tuple[int, int]] = {}
        self._node_edges: dict[str, set[_EdgeKey]] = {}
        # (dst_local, src_row, key, is_fwd) per (shard, relation) region,
        # then dst-sorted (with g=1 this is exactly the old per-relation
        # layout: dst_local == dst, one region set)
        directed: list[list[tuple[int, int, _EdgeKey, bool]]] = [
            [] for _ in range(g * num_rels)]
        for e in edges:
            srow = self._id_to_idx.get(e.src)
            drow = self._id_to_idx.get(e.dst)
            if srow is None or drow is None:   # placeholder outside base rows
                continue
            key = (e.src, e.dst, int(e.kind))
            r = int(e.kind)
            directed[(drow // nps) * num_rels + r].append(
                (drow % nps, srow, key, True))
            directed[(srow // nps) * num_rels + r].append(
                (srow % nps, drow, key, False))
            self._node_edges.setdefault(e.src, set()).add(key)
            self._node_edges.setdefault(e.dst, set()).add(key)
        slots_by_key: dict[_EdgeKey, dict[bool, int]] = {}
        self._free_edge_slots: list[list[int]] = []
        for region in range(g * num_rels):
            gi, r = divmod(region, num_rels)
            base = gi * pe_shard
            ents = directed[region]
            ents.sort(key=lambda t: t[0])   # stable: dst_local only
            fill = base + int(offs[r])
            for dloc, srow, key, fwd in ents:
                esrc[fill], edst[fill], emask[fill] = srow, dloc, 1.0
                erel[fill] = r
                slots_by_key.setdefault(key, {})[fwd] = fill
                fill += 1
            # per-(shard, relation) free slot lists (allocation stays
            # region-local, which keeps the static offsets valid)
            self._free_edge_slots.append(
                list(range(base + int(offs[r + 1]) - 1, fill - 1, -1)))
        for key, by_dir in slots_by_key.items():
            self._edge_slot[key] = (by_dir[True], by_dir[False])
        self._rel_offsets: tuple[int, ...] = offs
        self._esrc_dev = jnp.asarray(esrc)
        self._edst_dev = jnp.asarray(edst)
        self._erel_dev = jnp.asarray(erel)
        self._emask_dev = jnp.asarray(emask)
        self._kind_dev = jnp.asarray(self.snapshot.node_kind)
        self._nmask_dev = jnp.asarray(self.snapshot.node_mask)
        # directed slot -> (src_row, dst_local, rel_kind, mask)
        self._pending_edges: dict[int, tuple[int, int, int, int]] = {}
        # a fresh re-mirror IS dst-sorted per slice; in-place churn
        # (_packed_gnn_delta) forfeits the promise until the next one
        self._slices_sorted = True
        self._last_gnn: tuple | None = None
        self._apply_sharding()   # place the fresh mirror on the mesh
        self._quant_refresh()    # graft-tide: re-derive the quant mirror

    # -- graft-tide: quantized feature mirror ------------------------------

    def _quant_refresh(self) -> None:
        """(Re)derive the HBM-resident quantized feature table + per-
        column scale from host-truth features — at every mirror
        (re)build and at shield restore adoption. Deterministic given
        the snapshot, so a restore reproduces the exact serving table
        without packing it into the shield snapshot. The scale freezes
        until the next refresh; per-tick delta rows quantize against the
        frozen scale (clipped — within the tier's tolerance contract)."""
        if not self._feat_quant or getattr(self, "_mirror_sharded", False):
            self._features_q_dev = None
            self._feat_scale_dev = None
            self._feat_scale_host = None
            return
        from ..ops.pallas_segment import quantize_features
        q, scale = quantize_features(
            jnp.asarray(self.snapshot.features), self._feat_quant)
        self._features_q_dev = q
        self._feat_scale_dev = scale
        self._feat_scale_host = None if scale is None else np.asarray(scale)

    def _quant_rows(self, rows: list, pk: int):
        """The per-tick quantized feature delta: the aux rows' CURRENT
        host-truth features quantized against the frozen per-column
        scale, padded to the [pk, dim] delta bucket — scattered into the
        HBM-resident quant table in-kernel (same f_idx slots as the aux
        delta; padding drops)."""
        dim = self.snapshot.features.shape[1]
        out = np.zeros((pk, dim), np.float32)
        if rows:
            out[:len(rows)] = self.snapshot.features[rows]
        if self._feat_quant == "bfloat16":
            return jnp.asarray(out).astype(jnp.bfloat16)
        scale = self._feat_scale_host
        safe = np.where(scale > 0, scale, 1.0)
        q = np.clip(np.round(out / safe[None, :]), -127, 127)
        q = np.where(scale[None, :] > 0, q, 0.0)
        return jnp.asarray(q.astype(np.int8))

    # -- journal-driven mirror maintenance --------------------------------

    def _nodes_per_shard(self) -> int:
        return self.snapshot.padded_nodes // (
            self._graph_size() if self._mirror_sharded else 1)

    def _dst_region(self, kind: int, dst_row: int) -> int:
        """Free-list index of the region a directed slot targeting
        ``dst_row`` allocates from: (owner shard, relation) in sharded
        mode, relation alone otherwise."""
        if not self._mirror_sharded:
            return kind
        from ..graph.schema import RelationKind
        return (dst_row // self._nodes_per_shard()) * len(RelationKind) \
            + kind

    def _slot_region(self, kind: int, slot: int) -> int:
        """Region index of an EXISTING slot (owner from the slot space)."""
        if not self._mirror_sharded:
            return kind
        from ..graph.schema import RelationKind
        return (slot // self._pe_shard) * len(RelationKind) + kind

    def _dst_local(self, row: int) -> int:
        return row % self._nodes_per_shard() if self._mirror_sharded \
            else row

    def _mirror_add(self, src: str, dst: str, kind: int) -> None:
        key = (src, dst, kind)
        if key in self._edge_slot:
            return
        srow = self._id_to_idx.get(src)
        drow = self._id_to_idx.get(dst)
        if srow is None or drow is None:
            return   # endpoint removed later in this batch: edge is gone too
        rf = self._dst_region(kind, drow)   # fwd entry: dst-owner region
        rr = self._dst_region(kind, srow)   # rev entry: src-owner region
        free_f, free_r = self._free_edge_slots[rf], self._free_edge_slots[rr]
        if len(free_f) < (2 if rf == rr else 1) or len(free_r) < 1:
            # a region overflowed: full re-mirror with re-derived
            # capacities (the bucketed-layout fallback — the static
            # offsets can't stretch in place)
            self._mirror_init()
            return
        slot_f, slot_r = free_f.pop(), free_r.pop()
        self._edge_slot[key] = (slot_f, slot_r)
        self._node_edges.setdefault(src, set()).add(key)
        self._node_edges.setdefault(dst, set()).add(key)
        self._pending_edges[slot_f] = (srow, self._dst_local(drow), kind, 1)
        self._pending_edges[slot_r] = (drow, self._dst_local(srow), kind, 1)

    def _mirror_del(self, key: _EdgeKey) -> None:
        slots = self._edge_slot.pop(key, None)
        if slots is None:
            return
        src, dst, kind = key
        for nid in (src, dst):
            s = self._node_edges.get(nid)
            if s is not None:
                s.discard(key)
                if not s:
                    del self._node_edges[nid]
        for slot in slots:
            # back to ITS region (per-(shard, relation) in sharded mode)
            self._free_edge_slots[self._slot_region(kind, slot)].append(slot)
            self._pending_edges[slot] = (0, 0, -1, 0)

    def _drain_edges(self) -> None:
        recs, seq, truncated = self.store.journal_since(self._gnn_seq)
        if truncated:
            self._mirror_init()
            self._gnn_seq = self.store.journal_seq
            return
        self._apply_edge_records(recs)
        self._gnn_seq = max(seq, self._gnn_seq)

    def _apply_edge_records(self, recs: list) -> None:
        """Mirror one batch of store-journal records onto the edge mirror.
        Shared by the live drain above and the shield's write-ahead-log
        replay (rca/shield.py): replaying the same records through the
        same slot allocator reproduces the mirror bit-identically (free
        lists are part of the snapshot). Caller owns the cursor."""
        for rec in recs:
            op = rec[1]
            if op == "edge+":
                self._mirror_add(rec[2], rec[3], rec[4])
            elif op == "edge-":
                self._mirror_del((rec[2], rec[3], rec[4]))
            elif op == "node-":
                # store cascade-removes the node's edges without per-edge
                # records; mirror the cascade from the adjacency
                for key in list(self._node_edges.get(rec[2], ())):
                    self._mirror_del(key)

    # -- scoring -----------------------------------------------------------

    def _packed_gnn_delta(self, aux_rows: list[int]) -> tuple[np.ndarray, int, int]:
        from ..utils.padding import bucket_for
        pi = self.snapshot.padded_incidents
        pn = self.snapshot.padded_nodes
        pe = int(self._esrc_dev.shape[0])

        pk = bucket_for(max(len(aux_rows), 1), _DELTA_BUCKETS)
        f_idx = np.full(pk, pn, np.int32)
        kind_v = np.zeros(pk, np.int32)
        nmask_v = np.zeros(pk, np.int32)
        if aux_rows:
            f_idx[:len(aux_rows)] = aux_rows
            kind_v[:len(aux_rows)] = self.snapshot.node_kind[aux_rows]
            nmask_v[:len(aux_rows)] = self.snapshot.node_mask[
                aux_rows].astype(np.int32)

        ents = [(slot, srow, drow, rel, m)
                for slot, (srow, drow, rel, m) in self._pending_edges.items()]
        self._pending_edges = {}
        if len(ents) > _DELTA_BUCKETS[-1]:
            # a delta beyond the ladder would mint a fresh power-of-two
            # compile mid-serve; a full re-mirror (one upload, no compile
            # at unchanged pe) is cheaper and resets pending entirely
            self._mirror_init()
            ents = []
            # the re-mirror may have re-bucketed the edge arrays: the
            # padding sentinel below must be out of range of the NEW pe,
            # or it would zero a live slot (code-review r5)
            pe = int(self._esrc_dev.shape[0])
        if ents:
            # applying an in-place edge delta reuses slots out of dst
            # order: the sorted fast path is forfeit until the next full
            # re-mirror re-establishes it
            self._slices_sorted = False
        ek = bucket_for(max(len(ents), 1), _DELTA_BUCKETS)
        e_idx = np.full(ek, pe, np.int32)
        e_src = np.zeros(ek, np.int32)
        e_dst = np.zeros(ek, np.int32)
        e_rel = np.full(ek, -1, np.int32)
        e_mask = np.zeros(ek, np.int32)
        for j, (slot, s, d, r, m) in enumerate(ents):
            e_idx[j], e_src[j], e_dst[j] = slot, s, d
            e_rel[j], e_mask[j] = r, m

        ints = np.concatenate([
            f_idx, kind_v, nmask_v, e_idx, e_src, e_dst, e_rel, e_mask,
            self.snapshot.incident_nodes.astype(np.int32),
            self.snapshot.incident_mask.astype(np.int32),
        ]).astype(np.int32, copy=False)
        return ints, pk, ek

    def _packed_gnn_delta_sharded(self, aux_rows: list[int]
                                  ) -> tuple[np.ndarray, int, int]:
        """Per-shard packed delta for the sharded GNN tick
        (parallel/sharded_streaming.sharded_gnn_tick): aux (kind/nmask)
        deltas route to their node-owner shard, edge-slot deltas to their
        slot-owner shard, each with per-shard _DELTA_BUCKETS sub-buckets
        (compiled width = max over shards, so one hot shard doesn't
        retrace the others); the [Pi] incident tables ride replicated in
        every shard's row. Store-journal order is preserved WITHIN each
        shard — the router walks the pending maps in insertion order."""
        from ..parallel.sharded_streaming import route_node_delta
        from ..utils.padding import bucket_for
        g = self._graph_size()
        pi = self.snapshot.padded_incidents
        pn = self.snapshot.padded_nodes
        nps = pn // g

        f_idx, per_aux, pk = route_node_delta(
            [(r,) for r in aux_rows], nps, g, _DELTA_BUCKETS)
        kind_v = np.zeros((g, pk), np.int32)
        nmask_v = np.zeros((g, pk), np.int32)
        for gi, ents in enumerate(per_aux):
            for j, (row,) in enumerate(ents):
                kind_v[gi, j] = self.snapshot.node_kind[row]
                nmask_v[gi, j] = int(self.snapshot.node_mask[row])

        pe_shard = self._pe_shard
        per_edge: list[list] = [[] for _ in range(g)]
        for slot, (srow, dloc, rel, m) in self._pending_edges.items():
            per_edge[slot // pe_shard].append(
                (slot % pe_shard, srow, dloc, rel, m))
        self._pending_edges = {}
        if max((len(s) for s in per_edge), default=0) > _DELTA_BUCKETS[-1]:
            # a per-shard delta beyond the ladder would mint a fresh
            # power-of-two compile mid-serve; a full re-mirror (no compile
            # at unchanged shapes) resets pending entirely
            self._mirror_init()
            per_edge = [[] for _ in range(g)]
            pe_shard = self._pe_shard
        if any(per_edge):
            # in-place slot reuse breaks within-slice dst order until the
            # next full re-mirror
            self._slices_sorted = False
        ek = bucket_for(
            max(max((len(s) for s in per_edge), default=0), 1),
            _DELTA_BUCKETS)
        e_idx = np.full((g, ek), pe_shard, np.int32)
        e_src = np.zeros((g, ek), np.int32)
        e_dst = np.zeros((g, ek), np.int32)
        e_rel = np.full((g, ek), -1, np.int32)
        e_mask = np.zeros((g, ek), np.int32)
        for gi, shard_ents in enumerate(per_edge):
            for j, (sl, s, d, r, m) in enumerate(shard_ents):
                e_idx[gi, j], e_src[gi, j], e_dst[gi, j] = sl, s, d
                e_rel[gi, j], e_mask[gi, j] = r, m
        inc_n = np.broadcast_to(
            self.snapshot.incident_nodes.astype(np.int32), (g, pi))
        inc_m = np.broadcast_to(
            self.snapshot.incident_mask.astype(np.int32), (g, pi))
        ints = np.concatenate(
            [f_idx, kind_v, nmask_v, e_idx, e_src, e_dst, e_rel, e_mask,
             inc_n, inc_m], axis=1).astype(np.int32, copy=False)
        return ints, pk, ek

    def _sharded_tick_fn(self, pk: int, ek: int):
        """The sharded GNN tick for the CURRENT shapes. The sharded path
        runs the relation-bucketed XLA kernel by default; with
        settings.gnn_fused_tick the SHARD-LOCAL gather→matmul→segment
        portion promotes to the Pallas kernel while the halo assembly
        stays in XLA (graft-fuse) — the shield's kernel-fallback rung
        flips ``_use_fused`` off here exactly like the single-device
        tiers. ``settings.gnn_pallas`` alone keeps the historical
        single-device-only behavior."""
        from ..parallel.sharded_streaming import sharded_gnn_tick
        g = self._graph_size()
        return sharded_gnn_tick(
            self.mesh, self.snapshot.padded_nodes // g, self._pe_shard,
            self.snapshot.padded_incidents, pk, ek,
            rel_offsets=self._rel_offsets,
            slices_sorted=bool(self._slices_sorted),
            compute_dtype=self._compute_dtype,
            use_pallas=bool(self._use_fused))

    def _tick_handles(self, out: tuple) -> tuple:
        """The pipeline queue tracks the GNN tick's outputs: in gnn mode
        the base rules handles are never fetched, so the GNN probs are
        both the completion signal and the deferred-fetch surface. The
        tuple leads with the params GENERATION the tick dispatched
        against (graft-evolve): a deferred newest-tick fetch after a hot
        swap must report the generation that actually produced the
        verdict, not the one currently installed. The probs stay LAST —
        every pipeline readiness/stall probe reads ``handles[-1]``."""
        return self._last_gnn

    # -- graft-evolve: hot checkpoint swap ---------------------------------

    def _swap_params_locked(self, params, generation: int,
                            source: str = "") -> None:
        """Install new params under an ALREADY-HELD ``serve_lock`` — the
        multi-scorer atomic swap (rca/surge.swap_tenants_atomically)
        acquires every tenant's lock first, then flips each scorer
        through this seam. The swap is a reference replacement at a queue
        generation boundary: dispatch() reads ``self._params`` under
        ``serve_lock``, so in-flight ticks keep the OLD tree (they
        captured it at their own dispatch) and complete on it, while the
        next dispatch passes the new tree — same shapes/dtypes, so the
        jitted tick reuses its compiled executable (no retrace). Shape or
        structure drift is rejected up front: silently retracing the
        serving tick mid-stream is exactly the hiccup warm() exists to
        prevent."""
        new = jax.tree_util.tree_map(jnp.asarray, params)
        old_leaves, old_def = jax.tree_util.tree_flatten(self._params)
        new_leaves, new_def = jax.tree_util.tree_flatten(new)
        if old_def != new_def or any(
                a.shape != b.shape or a.dtype != b.dtype
                for a, b in zip(old_leaves, new_leaves)):
            raise ValueError(
                "hot swap rejected: candidate params tree/shapes differ "
                "from the serving checkpoint (a swap must reuse the "
                "compiled tick — retrain with the serving model config)")
        self._params_prev = (self._params, self.params_generation,
                             self._params_source)
        self._params = new
        self.params_generation = int(generation)
        self._params_source = source
        obs_metrics.LEARN_GENERATION.set(float(self.params_generation))
        obs_scope.FLIGHT_RECORDER.note_event(
            "params_swap", generation=self.params_generation,
            source=source, backend="gnn")
        log.info("params_swapped", generation=self.params_generation,
                 source=source)

    def swap_params(self, params, generation: "int | None" = None,
                    source: str = "") -> int:
        """Hot-swap the serving checkpoint without dropping in-flight
        ticks (see :meth:`_swap_params_locked`). Returns the new
        generation. Unshielded entry point — the ShieldedScorer shadows
        this with a WAL-journaled variant so crash recovery replays onto
        the correct generation."""
        with self.serve_lock:
            gen = (self.params_generation + 1 if generation is None
                   else int(generation))
            self._swap_params_locked(params, gen, source=source)
        obs_metrics.LEARN_SWAPS.inc()
        return gen

    def rollback_params(self) -> "int | None":
        """Revert to the previous params generation (post-swap nonfinite
        or accuracy regression). Returns the restored generation, or None
        when there is nothing to roll back to. The restored tree serves
        under a FRESH (monotonically advanced) generation number so the
        shield WAL replay stays ordered — replay applies any swap record
        newer than the state it restored."""
        with self.serve_lock:
            if self._params_prev is None:
                return None
            params, _old_gen, source = self._params_prev
            gen = self.params_generation + 1
            self._swap_params_locked(params, gen, source=source)
            self._params_prev = None   # one-deep: no rollback ping-pong
        obs_metrics.LEARN_ROLLBACKS.inc()
        return gen

    # -- graft-shield seams (snapshot/restore) -----------------------------

    _HOST_STATE_ATTRS = StreamingScorer._HOST_STATE_ATTRS + (
        "_gnn_seq", "_rel_offsets", "_slices_sorted",
        "_edge_slot", "_node_edges", "_free_edge_slots", "_pending_edges",
        "_mirror_sharded", "_pe_shard",
        # graft-evolve: the generation/source stamp travels with the
        # snapshot so a restore serves the generation it captured (the
        # params VALUES ride in the packed device arrays below)
        "params_generation", "_params_source",
    )

    _MIRROR_ARRAYS = 6   # kind/nmask + the four edge arrays

    def _resident_arrays(self) -> list:
        # the serving params are part of the resident state (graft-evolve):
        # packing their leaves into the snapshot makes crash recovery
        # restore the EXACT swapped checkpoint bit-for-bit — no reload
        # from a checkpoint file that may have moved on
        leaves = jax.tree_util.tree_leaves(self._params)
        return super()._resident_arrays() + [
            self._kind_dev, self._nmask_dev, self._esrc_dev,
            self._edst_dev, self._erel_dev, self._emask_dev] + leaves

    def _adopt_resident(self, parts: tuple) -> None:
        super()._adopt_resident(parts)
        m = 4 + self._MIRROR_ARRAYS
        (self._kind_dev, self._nmask_dev, self._esrc_dev, self._edst_dev,
         self._erel_dev, self._emask_dev) = (jnp.asarray(p)
                                             for p in parts[4:m])
        if len(parts) > m:
            # params leaves packed after the mirrors: unflatten with the
            # CURRENT tree structure (same model config by construction —
            # the shield matches scorer class before restoring)
            treedef = jax.tree_util.tree_structure(self._params)
            self._params = jax.tree_util.tree_unflatten(
                treedef, [jnp.asarray(p) for p in parts[m:]])
            self._params_prev = None
        self._last_gnn = None
        self._dma_h = None   # scratch: shapes may differ post-restore
        # the base call placed only ITS arrays (the mirror handles still
        # held pre-restore buffers then); re-place now that the restored
        # mirror is installed — device_put with an unchanged sharding is
        # free, so the unsharded path costs nothing
        self._apply_sharding()
        # graft-tide: the quant mirror re-derives from the restored host
        # truth (deterministic) instead of riding the packed snapshot
        self._quant_refresh()

    def _apply_sharding(self) -> None:
        super()._apply_sharding()
        if not getattr(self, "_mirror_sharded", False) or \
                getattr(self, "_esrc_dev", None) is None:
            return
        from jax.sharding import NamedSharding, PartitionSpec as P
        gsh = NamedSharding(self.mesh, P("graph"))
        self._kind_dev = jax.device_put(self._kind_dev, gsh)
        self._nmask_dev = jax.device_put(self._nmask_dev, gsh)
        self._esrc_dev = jax.device_put(self._esrc_dev, gsh)
        self._edst_dev = jax.device_put(self._edst_dev, gsh)
        self._erel_dev = jax.device_put(self._erel_dev, gsh)
        self._emask_dev = jax.device_put(self._emask_dev, gsh)

    def adopt_mesh(self, mesh) -> None:
        """graft-heal: live resharding for the GNN scorer. The base
        reshard re-derives features/evidence from host truth at the new
        placement; the edge mirror additionally RE-BUCKETS — the
        per-(shard, relation) regions, shard-local dst rows and shared
        static offsets are all functions of D, so ``_mirror_init``
        re-places every edge on its dst-owner shard under the new mesh
        (re-deriving the shared region capacities the partition.py way)
        and resets kind/nmask from the host-truth snapshot. A freshly
        re-mirrored layout is dst-sorted, exactly what a fresh D' build
        lays out — which is why post-heal GNN serving is
        verdict-identical to a fresh D' build (the graft-fleet churn
        contract: slot-reuse history differs, per-dst sums reorder at
        float tolerance)."""
        if mesh is not None and (
                "graph" not in getattr(mesh, "axis_names", ())
                or mesh.shape["graph"] <= 1):
            mesh = None
        # the OLD-layout edge regions cannot be placed on the NEW mesh
        # (their stacked slot space is sized for the old D): drop them so
        # the base reshard's _apply_sharding skips the mirror, then
        # rebuild at the new layout
        self._esrc_dev = None
        super().adopt_mesh(mesh)
        self._mirror_init()

    def _attest_arrays(self) -> list:
        # the aux mirrors are node-addressed with exact host truth; the
        # edge regions are NOT attested per shard (their slot layout is
        # allocation history, re-derivable but not host-mirrored row-wise)
        return super()._attest_arrays() + [
            ("_kind_dev", self.snapshot.node_kind),
            ("_nmask_dev", self.snapshot.node_mask)]

    def _pending_delta_count(self) -> int:
        # each pending edge entry is one directed slot in the packed
        # delta; in sharded mode the compiled width follows the MAX
        # per-shard count (per-shard sub-buckets bound the ladder)
        if getattr(self, "_mirror_sharded", False):
            per = [0] * self._graph_size()
            for slot in self._pending_edges:
                per[slot // self._pe_shard] += 1
            return super()._pending_delta_count() + max(per)
        return super()._pending_delta_count() + len(self._pending_edges)

    def dispatch(self) -> tuple:
        """Base fused tick (shared feature deltas + rules score), then the
        GNN tick on the UPDATED features. Returns the base device handles
        (unfetched); GNN outputs land in `_last_gnn`.

        graft-fuse: the edge-journal drain and the GNN delta pack now run
        BEFORE the base dispatch (the drained record set is identical —
        nothing appends to the store journal mid-dispatch), so on the
        columnar path the packed GNN ints fold into the base scorer's
        staged slab (`_staged_extra_ints`) and the whole tick — base
        delta, feature rows AND the GNN delta — pays ONE host→device
        transfer (PR 11's named follow-up). The sharded mirror keeps its
        per-shard [G, L] transfer. With settings.gnn_fused_tick the
        single-device tick itself runs as one Pallas kernel
        (`_gnn_fused_tick`)."""
        aux_rows = list(self._pending_feat.keys())
        self._drain_edges()
        if self._mirror_sharded:
            self._gnn_stage = None
            out = super().dispatch()
            span = self._last_tick_span   # opened by the base dispatch
            ints, pk, ek = self._packed_gnn_delta_sharded(aux_rows)
            tick = self._sharded_tick_fn(pk, ek)
            args = (self._params, self._features_dev, self._kind_dev,
                    self._nmask_dev, self._esrc_dev, self._edst_dev,
                    self._erel_dev, self._emask_dev, jnp.asarray(ints))
            self._scope_gnn(span, True, pk, ek, tick, args)
            (self._kind_dev, self._nmask_dev, self._esrc_dev,
             self._edst_dev, self._erel_dev, self._emask_dev, logits,
             probs) = tick(*args)
        else:
            ints, pk, ek = self._packed_gnn_delta(aux_rows)
            if self._feat_quant:
                # graft-tide: the quantized delta rows ride beside the
                # packed ints — consumed by _dispatch_dma this tick
                self._dma_stage_fq = self._quant_rows(aux_rows, pk)
            columnar = isinstance(self._pending_feat, FeatureStage)
            self._gnn_stage = ints if columnar else None
            try:
                out = super().dispatch()
            finally:
                self._gnn_stage = None
            span = self._last_tick_span
            ints_dev = self._staged_gnn_dev
            self._staged_gnn_dev = None
            if ints_dev is None:
                # dict-oracle path (or a sharded base tick): the GNN
                # delta pays its own transfer, exactly as before
                ints_dev = jnp.asarray(ints)
            pi = self.snapshot.padded_incidents
            args = (self._params, self._features_dev, self._kind_dev,
                    self._nmask_dev, self._esrc_dev, self._edst_dev,
                    self._erel_dev, self._emask_dev, ints_dev)
            self._scope_gnn(span, False, pk, ek, None, args)
            (self._kind_dev, self._nmask_dev, self._esrc_dev,
             self._edst_dev, self._erel_dev, self._emask_dev, logits,
             probs) = self._call_gnn_tick(args, pk, ek, pi, live=True)
        self._last_gnn = (self.params_generation, logits, probs)
        if span is not None:
            span.mark("gnn_dispatch")
        return out

    def _tick_entrypoint(self, args, pk: int, ek: int, pi: int,
                         sharded: bool = False) -> str:
        """Cost-model entrypoint of the tick variant ``_call_gnn_tick``
        would DISPATCH for these operands (graft-tide satellite): the
        roofline resolves its model from the variant actually serving —
        the DMA/bf16/int8 tiers price HBM tile traffic where the
        resident tiers price whole-operand reads, so labeling them all
        ``streaming.gnn_tick.fused`` would chart the wrong ceiling."""
        if sharded:
            return "streaming.gnn_tick.sharded"
        if self._dma_ok(args, pk, ek, pi):
            if self._feat_quant == "int8":
                return "streaming.gnn_tick.dma.int8"
            if self._feat_quant == "bfloat16":
                return "streaming.gnn_tick.dma.bf16"
            return "streaming.gnn_tick.dma"
        if self._fused_ok():
            return ("streaming.gnn_tick.fused.bf16"
                    if self._compute_dtype == "bfloat16"
                    else "streaming.gnn_tick.fused")
        return ("streaming.gnn_tick.bucketed" if self._use_bucketed
                else "streaming.gnn_tick")

    def _scope_tick_fn(self, entry: str, args, pk: int, ek: int, pi: int):
        """(callable, operands) matching the dispatched variant for the
        roofline's abstract trace. The DMA tiers take extra operands the
        composed layout doesn't carry (activation scratch, quant delta);
        stand-ins ride as ShapeDtypeStructs — the trace never touches
        resident buffers."""
        offs = self._rel_offsets
        if entry.startswith("streaming.gnn_tick.dma"):
            params, features = args[0], args[1]
            pn, dim = int(features.shape[0]), int(features.shape[1])
            hidden = int(params["embed_b"].shape[0])
            nb = min(self._dma_node_block, pn)
            h = jax.ShapeDtypeStruct((pn, hidden), jnp.float32)
            if not self._feat_quant:
                return (partial(_gnn_dma_tick, pk=pk, ek=ek, pi=pi,
                                rel_offsets=offs, node_block=nb,
                                compute_dtype=self._compute_dtype),
                        args + (h, h))
            qdt = jnp.int8 if self._feat_quant == "int8" else jnp.bfloat16
            qtable = jax.ShapeDtypeStruct((pn, dim), qdt)
            fq = jax.ShapeDtypeStruct((pk, dim), qdt)
            scale = (jax.ShapeDtypeStruct((dim,), jnp.float32)
                     if self._feat_quant == "int8" else None)
            return (partial(_gnn_dma_tick_q, pk=pk, ek=ek, pi=pi,
                            rel_offsets=offs, node_block=nb,
                            compute_dtype=self._compute_dtype,
                            feat_quant=self._feat_quant),
                    (params, qtable) + tuple(args[2:]) + (h, h, fq, scale))
        if entry.startswith("streaming.gnn_tick.fused"):
            return (partial(_gnn_fused_tick, pk=pk, ek=ek, pi=pi,
                            rel_offsets=offs,
                            compute_dtype=self._compute_dtype), args)
        return (partial(_gnn_tick, pk=pk, ek=ek, pi=pi,
                        **self._tick_statics()), args)

    def _scope_gnn(self, span, sharded: bool, pk: int, ek: int,
                   tick, args) -> None:
        """Roofline-model the GNN tick at its live compiled shapes (cached
        per shape key; abstract trace — the donated mirrors are not
        consumed). The GNN tick supersedes the rules tick as the roofline
        entrypoint this scorer reports: its verdict is the one served.
        ``tick=None`` (the single-device path) resolves the traced
        callable from the variant _call_gnn_tick would dispatch."""
        if span is None:
            return
        pi = self.snapshot.padded_incidents
        self._scope_entry = self._tick_entrypoint(args, pk, ek, pi,
                                                  sharded=sharded)
        self._scope_key = (self.snapshot.padded_nodes, pi,
                           int(self._esrc_dev.shape[0]), pk, ek, sharded)
        if tick is None:
            tick, args = self._scope_tick_fn(self._scope_entry, args,
                                             pk, ek, pi)
        obs_scope.ROOFLINE.model(self._scope_entry, self._scope_key,
                                 tick, args, pack=self._scope_pack)

    def _fetch_verdicts(self, handles, span, stats: dict,
                        queue_wait_s: float, dispatch_s: float) -> dict:
        """GnnRcaBackend.score_snapshot-shaped raw dict for live
        incidents. The base rescore()/rescore_newest() drive this —
        ``handles`` is this scorer's ``_tick_handles`` surface
        ``(params_gen, logits, probs)`` and only the probs pay the
        readback. Same caller-boundary contract as the rules fetch:
        exactly one device_get, dispatch/fetch timings split. The
        generation reported is the one the FETCHED tick dispatched
        against — after a hot swap, a deferred newest-tick fetch may
        legitimately serve the previous generation (in-flight ticks
        complete on old params)."""
        import time
        t2 = time.perf_counter()
        tick_gen = int(handles[0])
        self._fault_point("fetch")
        if span is not None:
            jax.block_until_ready(handles[-1])
            span.mark("execute")
        probs = np.asarray(jax.device_get(handles[-1]))
        fetch_s = time.perf_counter() - t2
        if span is not None:
            span.mark("fetch")
            exec_s = span.splits().get("execute", 0.0)
            self.scope.finalize(span, fetched=True)
            obs_scope.ROOFLINE.observe(self._scope_entry, self._scope_key,
                                       exec_s, pack=self._scope_pack)
        self.fetches += 1
        obs_metrics.SERVE_FETCHED_BYTES.inc(
            float(probs.nbytes), path="gnn_rescore")
        ids, rows = self.live_incidents()
        p = probs[rows]
        pred = p.argmax(axis=-1)
        return {
            "incident_ids": tuple(ids),
            "probs": p,
            "top_rule_index": pred,
            "any_match": pred != NUM_RULES,
            "top_confidence": p.max(axis=-1),
            "queue_wait_seconds": queue_wait_s,
            "dispatch_seconds": dispatch_s,
            "fetch_seconds": fetch_s,
            "device_seconds": queue_wait_s + dispatch_s + fetch_s,
            "params_generation": tick_gen,
            **stats,
        }

    def warm_gnn(self, delta_sizes: tuple[int, ...] = (64, 256),
                 edge_sizes: tuple[int, ...] = (64, 256, 1024)) -> None:
        """Pre-compile the GNN tick for the steady-state delta buckets so
        hot ticks never pay an XLA compile (same discipline as the base
        warm()). The edge ladder includes 1024: each pending edge packs two
        directed entries, so a coalesced churn tick touching >128 edges
        lands in that bucket — the serving bench does, and a mid-serve
        compile there is the exact hiccup this exists to prevent
        (code-review r5). Both sorted variants are warmed: fresh-mirror /
        post-rebuild ticks claim slices_sorted=True, the first in-place
        churn flips to False — neither transition may pay a mid-serve
        compile. All-dropped deltas, and the DONATED mirror positions get
        fresh zero stand-ins per call (the tick donates kind/nmask + the
        four edge arrays; the live handles must never flow in here —
        donation would invalidate the serving state). params and features
        are read-only and stay live. Shapes are captured under serve_lock
        — a concurrent rebuild swapping them one attribute at a time must
        not hand jit a mixed old/new shape set (same reason as base
        warm(), streaming.py)."""
        with self.serve_lock:
            pi = self.snapshot.padded_incidents
            pn = self.snapshot.padded_nodes
            pe = int(self._esrc_dev.shape[0])
            params = self._params
            features_dev = self._features_dev
            fused = self._fused_ok()
            # the fused kernel's fold is order-exact regardless of the
            # sorted promise — one variant covers both transitions
            variants = ([None] if fused else
                        [True, False] if self._use_bucketed else [False])
            inc_n = self.snapshot.incident_nodes.astype(np.int32, copy=True)
            inc_m = self.snapshot.incident_mask.astype(np.int32)
            sharded = bool(getattr(self, "_mirror_sharded", False))
            g = self._graph_size() if sharded else 1
            pe_shard = getattr(self, "_pe_shard", pe)
            offs = self._rel_offsets
            compute_dtype = self._compute_dtype if self._use_bucketed \
                else None
            columnar = isinstance(self._pending_feat, FeatureStage)
            width = self.width
        if sharded:
            self._warm_gnn_sharded(delta_sizes, edge_sizes, pi, pn, g,
                                   pe, pe_shard, offs, compute_dtype,
                                   params, features_dev, inc_n, inc_m)
            return
        dim = self.snapshot.features.shape[1]
        for ss in variants:
            for pk in delta_sizes:
                for ek in edge_sizes:
                    if self._warm_stop:
                        return
                    ints = np.concatenate([
                        np.full(pk, pn, np.int32), np.zeros(pk, np.int32),
                        np.zeros(pk, np.int32),
                        np.full(ek, pe, np.int32), np.zeros(ek, np.int32),
                        np.zeros(ek, np.int32), np.full(ek, -1, np.int32),
                        np.zeros(ek, np.int32),
                        inc_n, inc_m,
                    ]).astype(np.int32, copy=False)
                    if columnar:
                        # pre-compile the slab split carrying the GNN
                        # delta (single-transfer satellite): the live
                        # dispatch splits [base ints | f_rows | gnn ints]
                        from .streaming import _ROW_BUCKETS, _delta_pack
                        gi = ints.size
                        for rk in _ROW_BUCKETS[:2]:
                            li = pk + 2 * rk + 2 * rk * width
                            _delta_pack(
                                jnp.zeros(li + pk * dim + gi, jnp.int32),
                                li=li, pk=pk, dim=dim, gi=gi)
                    self._call_gnn_tick(
                        (params, features_dev,
                         jnp.zeros(pn, jnp.int32),
                         jnp.zeros(pn, jnp.float32),
                         jnp.zeros(pe, jnp.int32),
                         jnp.zeros(pe, jnp.int32),
                         jnp.full((pe,), -1, jnp.int32),
                         jnp.zeros(pe, jnp.float32),
                         jnp.asarray(ints)), pk, ek, pi,
                        slices_sorted=ss)

    def _sharded_gnn_standins(self, pn: int, pe: int):
        """Fresh zero stand-ins for the sharded tick's DONATED mirror
        positions, placed exactly like the live state (executables key on
        input shardings)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        gsh = NamedSharding(self.mesh, P("graph"))
        return (jax.device_put(jnp.zeros(pn, jnp.int32), gsh),
                jax.device_put(jnp.zeros(pn, jnp.float32), gsh),
                jax.device_put(jnp.zeros(pe, jnp.int32), gsh),
                jax.device_put(jnp.zeros(pe, jnp.int32), gsh),
                jax.device_put(jnp.full((pe,), -1, jnp.int32), gsh),
                jax.device_put(jnp.zeros(pe, jnp.float32), gsh))

    def _warm_gnn_sharded(self, delta_sizes, edge_sizes, pi, pn, g, pe,
                          pe_shard, offs, compute_dtype, params,
                          features_dev, inc_n, inc_m) -> None:
        """Sharded-tick warm: per-shard all-dropped [G, L] deltas at the
        same bucket ladder, both sorted variants, stand-ins placed on the
        mesh (the donated mirror must never see the live handles)."""
        from ..parallel.sharded_streaming import sharded_gnn_tick
        nps = pn // g
        inc_rep = (np.broadcast_to(inc_n, (g, pi)),
                   np.broadcast_to(inc_m, (g, pi)))
        for ss in (True, False):
            for pk in delta_sizes:
                for ek in edge_sizes:
                    if self._warm_stop:
                        return
                    ints = np.concatenate([
                        np.full((g, pk), nps, np.int32),
                        np.zeros((g, pk), np.int32),
                        np.zeros((g, pk), np.int32),
                        np.full((g, ek), pe_shard, np.int32),
                        np.zeros((g, ek), np.int32),
                        np.zeros((g, ek), np.int32),
                        np.full((g, ek), -1, np.int32),
                        np.zeros((g, ek), np.int32),
                        *inc_rep,
                    ], axis=1).astype(np.int32, copy=False)
                    tick = sharded_gnn_tick(
                        self.mesh, nps, pe_shard, pi, pk, ek,
                        rel_offsets=offs, slices_sorted=ss,
                        compute_dtype=compute_dtype,
                        use_pallas=bool(self._use_fused))
                    tick(params, features_dev,
                         *self._sharded_gnn_standins(pn, pe),
                         jnp.asarray(ints))

    def warm_growth(self) -> None:
        """Base growth shapes, then the GNN tick at every (pn, offsets,
        pi) a rebuild could land on — without this, a bucket-overflow
        rebuild mid-serve pays a fresh _gnn_tick compile, the exact
        hiccup the re-arm machinery exists to prevent (code-review r5).
        Post-rebuild dispatches always use the smallest delta buckets
        (pending state is reset by _init_from_store), so only those are
        warmed. Edge shapes warm at the CURRENT offsets and at the
        offsets a re-mirror of the current store would derive
        (_mirror_offsets_now — the same derivation the rebuild runs);
        per-relation next-bucket combos are deliberately not enumerated,
        the combinatorics would swamp the warm budget for a rare single
        compile. Post-rebuild ticks run on a freshly dst-sorted mirror,
        so the sorted variant is what gets warmed here."""
        super().warm_growth()
        shapes = {(cpn, cpi) for cpn, cpi, _w, _pw, _d
                  in self._growth_shape_combos()}
        with self.serve_lock:
            dim = self.snapshot.features.shape[1]
            offs_cur = self._rel_offsets
            offs_now = self._mirror_offsets_now()
        pk = ek = _DELTA_BUCKETS[0]
        for cpn, cpi in shapes:
            for offs in {offs_cur, offs_now}:
                if self._warm_stop:
                    return
                pe_shard = max(int(offs[-1]), 1)
                if self._graph_sharded(cpn, cpi):
                    # rebuilds at divisible shapes stay sharded: warm the
                    # mesh-resident tick at the rebuild-derived offsets
                    from jax.sharding import NamedSharding, PartitionSpec
                    from ..parallel.sharded_streaming import (
                        sharded_gnn_tick)
                    g = self._graph_size()
                    cpe = pe_shard * g
                    ints = np.concatenate([
                        np.full((g, pk), cpn // g, np.int32),
                        np.zeros((g, pk), np.int32),
                        np.zeros((g, pk), np.int32),
                        np.full((g, ek), pe_shard, np.int32),
                        np.zeros((g, ek), np.int32),
                        np.zeros((g, ek), np.int32),
                        np.full((g, ek), -1, np.int32),
                        np.zeros((g, ek), np.int32),
                        np.zeros((g, 2 * cpi), np.int32),
                    ], axis=1).astype(np.int32, copy=False)
                    gsh = NamedSharding(self.mesh, PartitionSpec("graph"))
                    feats = jax.device_put(
                        jnp.zeros((cpn, dim), jnp.float32), gsh)
                    tick = sharded_gnn_tick(
                        self.mesh, cpn // g, pe_shard, cpi, pk, ek,
                        rel_offsets=offs, slices_sorted=True,
                        compute_dtype=self._compute_dtype
                        if self._use_bucketed else None,
                        use_pallas=bool(self._use_fused))
                    # graft-audit: allow[lock-guard] warm pre-compile reads whichever generation is current; a concurrent swap at worst triggers one re-warm
                    tick(self._params, feats,
                         *self._sharded_gnn_standins(cpn, cpe),
                         jnp.asarray(ints))
                    continue
                cpe = pe_shard
                ints = np.concatenate([
                    np.full(pk, cpn, np.int32), np.zeros(pk, np.int32),
                    np.zeros(pk, np.int32),
                    np.full(ek, cpe, np.int32), np.zeros(ek, np.int32),
                    np.zeros(ek, np.int32), np.full(ek, -1, np.int32),
                    np.zeros(ek, np.int32),
                    np.zeros(2 * cpi, np.int32),
                ]).astype(np.int32, copy=False)
                self._call_gnn_tick(
                    # graft-audit: allow[lock-guard] warm pre-compile reads whichever generation is current; a concurrent swap at worst triggers one re-warm
                    (self._params,
                     jnp.zeros((cpn, dim), jnp.float32),
                     jnp.zeros(cpn, jnp.int32),
                     jnp.zeros(cpn, jnp.float32),
                     jnp.zeros(cpe, jnp.int32),
                     jnp.zeros(cpe, jnp.int32),
                     jnp.full((cpe,), -1, jnp.int32),
                     jnp.zeros(cpe, jnp.float32),
                     jnp.asarray(ints)), pk, ek, cpi,
                    rel_offsets=offs, slices_sorted=True)

    def warm_serving(self) -> None:
        super().warm_serving()
        try:
            self.warm_gnn()
        except Exception as exc:  # graft-audit: allow[broad-except] best-effort warm: serving stays correct, just pays the compile
            log.warning("warm_gnn_failed", error=str(exc))

    # -- introspection (tests) ---------------------------------------------

    def mirror_edge_rows(self) -> set[tuple[int, int]]:
        """Live directed (src_row, dst_row) pairs per the HOST mirror maps
        — used by tests to compare against the store's edge set."""
        out: set[tuple[int, int]] = set()
        for (src, dst, _kind) in self._edge_slot:
            srow = self._id_to_idx.get(src)
            drow = self._id_to_idx.get(dst)
            if srow is not None and drow is not None:
                out.add((srow, drow))
                out.add((drow, srow))
        return out
