"""Streaming incremental re-scoring (BASELINE configs[4]).

Steady-state path for pod churn at ~1k events/sec: the snapshot's feature
matrix lives in device HBM; churn deltas are applied as a single padded
scatter-set per tick (no re-extraction of 50k nodes, no re-upload of the
13MB feature matrix), and re-scoring reuses the resident edge arrays.
Structural deltas (pod reschedules = SCHEDULED_ON retargets) mutate the
snapshot's COO arrays in place through an edge-position index and only
re-run the vectorized numpy prep join (~ms), never a full snapshot rebuild.
"""
from __future__ import annotations

import time
from functools import partial
from typing import Iterable

import numpy as np

import jax
import jax.numpy as jnp

from ..config import Settings, get_settings
from ..graph.schema import RelationKind
from ..graph.snapshot import GraphSnapshot, build_snapshot, extract_node_features
from ..graph.store import EvidenceGraphStore
from ..utils.padding import bucket_for
from .tpu_backend import (
    _PAIR_WIDTH_BUCKETS, DeviceBatch, dense_evidence_table, evidence_coo,
    evidence_layout, pair_tables,
)

_DELTA_BUCKETS = (64, 256, 1024, 4096, 16384)


@partial(jax.jit, static_argnames=("padded_incidents", "pair_width"))
def _update_and_score(features, idx, rows, ev_idx, ev_cnt, ev_pair_slot,
                      chain, padded_incidents: int, pair_width: int):
    """One fused device call per tick: apply the padded feature delta, then
    score — halves per-tick dispatches vs update-then-score (each dispatch
    costs real latency on a tunneled TPU). The caller replaces its features
    handle with the returned buffer. No buffer donation: the axon-tunneled
    backend measurably slows down with donated inputs, and the on-device
    [Pn, DIM] copy is ~µs."""
    from .tpu_backend import _aggregate, finish_scores

    features = features.at[idx].set(rows, mode="drop")
    counts, per_row_max = _aggregate(
        features, ev_idx, ev_cnt, ev_pair_slot, padded_incidents, pair_width)
    counts = counts + jnp.minimum(chain, 0.0)[:, None]
    return (features,) + finish_scores(counts, per_row_max, padded_incidents)


class StreamingScorer:
    """Device-resident scorer with incremental delta application."""

    def __init__(self, store: EvidenceGraphStore,
                 settings: Settings | None = None) -> None:
        self.settings = settings or get_settings()
        self.store = store
        self.snapshot: GraphSnapshot = build_snapshot(store, self.settings)
        self._id_to_idx = {nid: i for i, nid in enumerate(self.snapshot.node_ids)}
        nodes, _ = store._raw()
        self._nodes_by_id = {node.id: node for node in nodes}
        self._features_dev = jnp.asarray(self.snapshot.features)
        # evidence COO is invariant under reschedules — computed once, and
        # cached so structural flushes re-run ONLY the pair join (the dense
        # evidence table and its device upload stay resident)
        self._ev_coo = evidence_coo(self.snapshot)
        pi = self.snapshot.padded_incidents
        self._layout = evidence_layout(self._ev_coo[0], pi)
        ev_idx, ev_cnt = dense_evidence_table(*self._ev_coo, pi,
                                              layout=self._layout)
        ev_pair_slot, pair_width = pair_tables(self.snapshot, *self._ev_coo,
                                               layout=self._layout)
        self._batch = DeviceBatch(
            num_incidents=self.snapshot.num_incidents, padded_incidents=pi,
            ev_idx=ev_idx, ev_cnt=ev_cnt, ev_pair_slot=ev_pair_slot,
            pair_width=pair_width, features=self.snapshot.features)
        self._ev_args = (jnp.asarray(ev_idx), jnp.asarray(ev_cnt))
        self._pair_args = self._upload_pairs()
        # edge-position index for SCHEDULED_ON retargets: pod idx -> positions
        self._sched_pos: dict[int, list[int]] = {}
        live = self.snapshot.edge_mask > 0
        for pos in np.nonzero(
                (self.snapshot.edge_rel == int(RelationKind.SCHEDULED_ON)) & live)[0]:
            from ..graph.schema import EntityKind
            src = int(self.snapshot.edge_src[pos])
            dst = int(self.snapshot.edge_dst[pos])
            pod = src if self.snapshot.node_kind[src] == int(EntityKind.POD) else dst
            self._sched_pos.setdefault(pod, []).append(int(pos))
        self._pending_idx: list[int] = []
        self._pending_rows: list[np.ndarray] = []
        self._structural_dirty = False

    def _upload_pairs(self) -> tuple:
        b = self._batch
        # no block_until_ready: XLA orders the h2d copies before first use,
        # and forcing them costs a ~70 ms sync per structural flush on the
        # dev tunnel
        return (jnp.asarray(b.ev_pair_slot),)

    # -- delta ingestion --------------------------------------------------

    def update_nodes(self, node_ids: Iterable[str]) -> int:
        """Queue feature re-extraction for nodes whose properties changed."""
        n = 0
        for nid in node_ids:
            idx = self._id_to_idx.get(nid)
            node = self._nodes_by_id.get(nid)
            if idx is None or node is None:
                continue
            row = extract_node_features(node)
            self.snapshot.features[idx] = row  # keep host copy coherent
            self._pending_idx.append(idx)
            self._pending_rows.append(row)
            n += 1
        return n

    def reschedule_pod(self, pod_id: str, new_node_id: str) -> bool:
        """Retarget the pod's SCHEDULED_ON edges in the COO arrays."""
        pod = self._id_to_idx.get(pod_id)
        new_node = self._id_to_idx.get(new_node_id)
        if pod is None or new_node is None:
            return False
        for pos in self._sched_pos.get(pod, ()):
            if self.snapshot.edge_src[pos] == pod:      # forward pod->node
                self.snapshot.edge_dst[pos] = new_node
            else:                                        # reversed duplicate
                self.snapshot.edge_src[pos] = new_node
        self._structural_dirty = True
        return True

    # -- scoring ----------------------------------------------------------

    def _pending_delta(self) -> tuple[np.ndarray, np.ndarray]:
        """Drain queued feature updates into padded (idx, rows) arrays."""
        k = len(self._pending_idx)
        pk = bucket_for(max(k, 1), _DELTA_BUCKETS)
        pn = self.snapshot.padded_nodes
        idx = np.full(pk, pn, dtype=np.int32)      # out-of-range -> dropped
        rows = np.zeros((pk, self.snapshot.features.shape[1]), np.float32)
        if k:
            idx[:k] = self._pending_idx
            rows[:k] = np.stack(self._pending_rows)
            self._pending_idx.clear()
            self._pending_rows.clear()
        return idx, rows

    def _refresh_pairs(self) -> None:
        # reschedules only retarget SCHEDULED_ON edges: the evidence table
        # is untouched, so refresh just the pair tables
        from dataclasses import replace
        # never SHRINK pair_width mid-stream: a smaller bucket would be a
        # program warm() hasn't compiled. The floor goes INTO pair_tables so
        # the "no node" sentinel is stamped with the clamped width — a
        # sentinel stamped with a smaller, unclamped width would land in
        # range of the wider compiled one_hot and count phantom pods.
        ev_pair_slot, pair_width = pair_tables(
            self.snapshot, *self._ev_coo, layout=self._layout,
            min_width=self._batch.pair_width)
        self._batch = replace(
            self._batch, ev_pair_slot=ev_pair_slot, pair_width=pair_width)
        self._pair_args = self._upload_pairs()
        self._structural_dirty = False

    def warm(self, delta_sizes: tuple[int, ...] = (64, 256)) -> None:
        """Pre-compile the fused tick program for the given delta buckets so
        the first real tick doesn't pay a compile (each distinct padded
        delta size is a distinct XLA program). Also warms the NEXT
        pair-width bucket: a reschedule spreading one incident's pods onto a
        new node can bump pair_width mid-stream, and the hot loop must not
        pay that compile either."""
        if not delta_sizes:
            return
        pn = self.snapshot.padded_nodes
        dim = self.snapshot.features.shape[1]
        chain = jnp.zeros((self._batch.padded_incidents,), jnp.float32)
        cur_w = self._batch.pair_width
        next_w = next((w for w in _PAIR_WIDTH_BUCKETS if w > cur_w), cur_w)
        out = None
        for pk in delta_sizes:
            idx = np.full(pk, pn, dtype=np.int32)   # all-dropped delta
            rows = np.zeros((pk, dim), np.float32)
            for pw in {cur_w, next_w}:
                out = _update_and_score(
                    self._features_dev, jnp.asarray(idx), jnp.asarray(rows),
                    *self._ev_args, *self._pair_args, chain,
                    padded_incidents=self._batch.padded_incidents,
                    pair_width=pw)
        if out is not None:
            self._features_dev = out[0]   # no-op update; keep handle fresh

    def dispatch(self) -> tuple:
        """Flush pending deltas and enqueue one scoring pass; returns the
        device result handles without a host fetch. The steady-state tick
        path (feature deltas only) is ONE fused device call: apply the
        padded delta + score. On co-located hosts the fetch is
        microseconds, but it can be overlapped/batched (the dev tunnel
        charges ~75 ms per synchronous fetch — see tpu_backend.dispatch)."""
        if self._structural_dirty:
            self._refresh_pairs()  # rare path; the feature delta rides the
                                   # fused call below either way
        chain = jnp.zeros((self._batch.padded_incidents,), jnp.float32)
        idx, rows = self._pending_delta()
        out = _update_and_score(
            self._features_dev, jnp.asarray(idx), jnp.asarray(rows),
            *self._ev_args, *self._pair_args, chain,
            padded_incidents=self._batch.padded_incidents,
            pair_width=self._batch.pair_width,
        )
        self._features_dev = out[0]
        return out[1:]

    def rescore(self) -> dict:
        stats = {"feature_updates": len(self._pending_idx),
                 "structural_refresh": self._structural_dirty}
        t0 = time.perf_counter()
        if self._structural_dirty:
            self._refresh_pairs()
        flush_s = time.perf_counter() - t0
        t1 = time.perf_counter()
        out = self.dispatch()
        conds, matched, scores, top_idx, any_match, top_conf, top_score = (
            jax.device_get(out))
        device_s = time.perf_counter() - t1
        n = self.snapshot.num_incidents
        return {
            "incident_ids": self.snapshot.incident_ids,
            "conditions": conds[:n],
            "matched": matched[:n],
            "scores": scores[:n],
            "top_rule_index": top_idx[:n],
            "any_match": any_match[:n],
            "top_confidence": top_conf[:n],
            "top_score": top_score[:n],
            "flush_seconds": flush_s,
            "device_seconds": device_s,
            **stats,
        }
